"""The north star, falsifiable (VERDICT r2 next-#1): REAL images learned
end-to-end through the DAG machinery — sklearn's handwritten-digit scans
(the offline stand-in for the reference's digit-recognizer Kaggle
example, reference examples/digit-recognizer/Readme.md), driven
split -> jax_train -> infer_classify -> valid_classify to >=95% valid
accuracy, scores landing on the task and Model rows."""

import os

import numpy as np
import pytest

EXAMPLE = os.path.join(os.path.dirname(__file__), '..', 'examples',
                       'digits')


class TestDigitsDataset:
    def test_real_images(self):
        from mlcomp_tpu.train.data import create_dataset
        data = create_dataset('digits')
        x = np.concatenate([data['x_train'], data['x_valid']])
        y = np.concatenate([data['y_train'], data['y_valid']])
        assert len(x) == 1797                      # the real UCI set
        assert x.shape[1:] == (8, 8, 1)
        assert set(np.unique(y)) == set(range(10))
        assert 0.0 <= x.min() and x.max() <= 1.0
        # real scans, not prototypes+noise: same-class samples differ
        sevens = x[y == 7]
        assert np.abs(sevens[0] - sevens[1]).max() > 0.1
        assert data['source'] == 'sklearn.load_digits'

    def test_fold_csv_split(self, tmp_path):
        import pandas as pd
        from mlcomp_tpu.train.data import create_dataset
        folds = np.arange(1797) % 5
        p = tmp_path / 'fold.csv'
        pd.DataFrame({'fold': folds}).to_csv(p, index=False)
        data = create_dataset('digits', fold_csv=str(p), fold_number=2)
        assert len(data['x_valid']) == int((folds == 2).sum())
        assert len(data['x_train']) == 1797 - len(data['x_valid'])

    def test_fold_csv_row_mismatch_raises(self, tmp_path):
        import pandas as pd
        from mlcomp_tpu.train.data import create_dataset
        p = tmp_path / 'fold.csv'
        pd.DataFrame({'fold': [0, 1, 2]}).to_csv(p, index=False)
        with pytest.raises(ValueError, match='expected 1797'):
            create_dataset('digits', fold_csv=str(p))


class TestRealDataLearning:
    def test_digits_dag_to_95_percent(self, session):
        """The full example DAG on real data: every task Success, valid
        accuracy >= 0.95 written to task.score and model.score_local,
        gallery ReportImg rows produced."""
        from mlcomp_tpu.db.enums import TaskStatus
        from mlcomp_tpu.db.providers import (
            ModelProvider, ReportImgProvider, TaskProvider,
        )
        from mlcomp_tpu.server.create_dags.standard import dag_standard
        from mlcomp_tpu.utils.io import yaml_load
        from mlcomp_tpu.worker.tasks import execute_by_id

        config = yaml_load(file=os.path.join(EXAMPLE, 'config.yml'))
        dag, tasks = dag_standard(session, config, upload_folder=EXAMPLE)
        tp = TaskProvider(session)
        for name in ('prepare', 'split', 'train', 'infer', 'valid'):
            for tid in tasks[name]:
                execute_by_id(tid, exit=False, session=session)
                assert tp.by_id(tid).status == int(TaskStatus.Success), \
                    f'task {name} did not succeed'

        valid_task = tp.by_id(tasks['valid'][0])
        assert valid_task.score is not None
        assert valid_task.score >= 0.95, (
            f'real-data valid accuracy {valid_task.score:.4f} < 0.95')

        model = ModelProvider(session).by_name('digits_mlp')
        assert model is not None
        assert model.score_local >= 0.95

        train_task = tp.by_id(tasks['train'][0])
        imgs = ReportImgProvider(session).get({'task': train_task.id})
        assert imgs['total'] > 0, 'no gallery ReportImg rows from training'


class TestCifar10Converter:
    """scripts/cifar10_to_npz.py: standard CIFAR python pickles ->
    the train/data.py 'cifar10' npz contract."""

    def _fake_cifar(self, root, n_per_batch=4):
        import pickle
        rng = np.random.RandomState(0)
        folder = os.path.join(root, 'cifar-10-batches-py')
        os.makedirs(folder, exist_ok=True)
        truth = {}
        for name in [f'data_batch_{i}' for i in range(1, 6)] + \
                ['test_batch']:
            data = rng.randint(0, 256, (n_per_batch, 3072), dtype=np.uint8)
            labels = rng.randint(0, 10, n_per_batch).tolist()
            truth[name] = (data, labels)
            with open(os.path.join(folder, name), 'wb') as fh:
                pickle.dump({b'data': data, b'labels': labels}, fh)
        return folder, truth

    def test_folder_and_tar_roundtrip(self, tmp_path):
        import sys
        import tarfile
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..',
                                        'scripts'))
        import cifar10_to_npz as conv
        folder, truth = self._fake_cifar(str(tmp_path))
        out = str(tmp_path / 'cifar10.npz')
        info = conv.convert(folder, out, expect=(20, 4))
        assert info['train'] == 20 and info['test'] == 4
        data = np.load(out)
        assert data['x_train'].shape == (20, 32, 32, 3)
        assert data['x_train'].dtype == np.uint8
        # pixel fidelity: CHW->HWC transpose of batch 1 row 0
        want = truth['data_batch_1'][0][0].reshape(3, 32, 32)
        np.testing.assert_array_equal(data['x_train'][0],
                                      want.transpose(1, 2, 0))
        # tar path produces identical output
        tar = str(tmp_path / 'cifar-10-python.tar.gz')
        with tarfile.open(tar, 'w:gz') as t:
            t.add(folder, arcname='cifar-10-batches-py')
        out2 = str(tmp_path / 'cifar10_tar.npz')
        conv.convert(tar, out2, expect=(20, 4))
        data2 = np.load(out2)
        np.testing.assert_array_equal(data['x_train'], data2['x_train'])
        np.testing.assert_array_equal(data['y_test'], data2['y_test'])

    def test_loader_consumes_converter_output(self, tmp_path):
        """The npz feeds the 'cifar10' dataset loader (real path)."""
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..',
                                        'scripts'))
        import cifar10_to_npz as conv
        from mlcomp_tpu.train.data import create_dataset
        folder, _ = self._fake_cifar(str(tmp_path))
        out = str(tmp_path / 'cifar10.npz')
        conv.convert(folder, out, expect=(20, 4))
        data = create_dataset('cifar10', path=out)
        assert data['source'] == out
        assert data['x_train'].shape == (20, 32, 32, 3)
        assert data['x_train'].dtype == np.float32
        assert data['x_train'].max() <= 1.0

    def test_missing_batch_raises(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..',
                                        'scripts'))
        import cifar10_to_npz as conv
        folder, _ = self._fake_cifar(str(tmp_path))
        os.remove(os.path.join(folder, 'data_batch_3'))
        with pytest.raises(FileNotFoundError, match='data_batch_3'):
            conv.convert(folder, str(tmp_path / 'o.npz'), expect=(16, 4))


def _cifar_npz_path():
    import mlcomp_tpu
    explicit = os.environ.get('CIFAR10_NPZ')
    if explicit:
        return explicit if os.path.exists(explicit) else None
    default = os.path.join(mlcomp_tpu.DATA_FOLDER, 'cifar10.npz')
    return default if os.path.exists(default) else None


@pytest.mark.real_cifar
@pytest.mark.slow
class TestCifar10NorthStar:
    """BASELINE.json's north star, armed for the day the archive shows
    up (zero-egress image; run `python scripts/cifar10_to_npz.py
    <cifar-10-python.tar.gz>` then `CIFAR10_NPZ=... pytest -m
    real_cifar`): the examples/cifar10 DAG trains ResNet-18 through the
    REAL machinery to >= 94% valid accuracy."""

    def test_cifar10_dag_reaches_94(self, session):
        npz = _cifar_npz_path()
        if npz is None:
            pytest.skip('real CIFAR-10 npz not present '
                        '(CIFAR10_NPZ or DATA_FOLDER/cifar10.npz)')
        from mlcomp_tpu.db.enums import TaskStatus
        from mlcomp_tpu.db.providers import TaskProvider
        from mlcomp_tpu.server.create_dags.standard import dag_standard
        from mlcomp_tpu.utils.io import yaml_load
        from mlcomp_tpu.worker.tasks import execute_by_id

        folder = os.path.join(os.path.dirname(__file__), '..',
                              'examples', 'cifar10')
        config = yaml_load(file=os.path.join(folder, 'config.yml'))
        train = config['executors']['train']
        # the example ships a 5-epoch smoke schedule; the north star
        # needs the full recipe (~40 epochs of sgd+cosine reaches
        # 94-95% with pad-crop/flip on ResNet-18)
        train['stages'][0]['epochs'] = int(
            os.environ.get('CIFAR_EPOCHS', '40'))
        train['dataset'] = {'name': 'cifar10', 'path': npz}
        for name in ('infer', 'valid'):
            config['executors'][name]['dataset'] = {
                'name': 'cifar10', 'path': npz}
        dag, tasks = dag_standard(session, config)
        tp = TaskProvider(session)
        for name in ('train', 'infer', 'valid'):
            for tid in tasks[name]:
                execute_by_id(tid, exit=False, session=session)
                assert tp.by_id(tid).status == int(TaskStatus.Success)
        valid_task = tp.by_id(tasks['valid'][0])
        assert valid_task.score is not None
        assert valid_task.score >= 0.94, (
            f'north star missed: valid accuracy '
            f'{valid_task.score:.4f} < 0.94')


SEG_EXAMPLE = os.path.join(os.path.dirname(__file__), '..', 'examples',
                           'digits_segmentation')


class TestRealSegmentation:
    def test_digits_segmentation_dag_to_iou(self, session):
        """BASELINE config #5 stand-in (VERDICT r4 next-#6): REAL digit
        scans, masks derived by foreground threshold, driven
        split -> two unet trains -> infer_valid -> ensemble
        valid_segment to a stated IoU; scores on task + Model rows,
        worst-dice overlay gallery rows produced."""
        from mlcomp_tpu.db.enums import TaskStatus
        from mlcomp_tpu.db.providers import (
            ModelProvider, ReportImgProvider, TaskProvider,
        )
        from mlcomp_tpu.server.create_dags.standard import dag_standard
        from mlcomp_tpu.utils.io import yaml_load
        from mlcomp_tpu.worker.tasks import execute_by_id

        config = yaml_load(
            file=os.path.join(SEG_EXAMPLE, 'config.yml'))
        dag, tasks = dag_standard(session, config,
                                  upload_folder=SEG_EXAMPLE)
        tp = TaskProvider(session)
        order = ('prepare', 'split', 'train_a', 'train_b', 'valid_a',
                 'valid_ensemble')
        for name in order:
            for tid in tasks[name]:
                execute_by_id(tid, exit=False, session=session)
                assert tp.by_id(tid).status == \
                    int(TaskStatus.Success), f'task {name} failed'

        single = tp.by_id(tasks['valid_a'][0])
        ensemble = tp.by_id(tasks['valid_ensemble'][0])
        assert single.score is not None and single.score >= 0.70, (
            f'single-unet IoU {single.score} < 0.70')
        assert ensemble.score is not None and ensemble.score >= 0.75, (
            f'ensemble IoU {ensemble.score} < 0.75')

        model = ModelProvider(session).by_name('dseg_unet_a')
        assert model is not None and model.score_local == single.score

        # overlay galleries: from training's report_imgs AND from the
        # valid_segment scoring passes
        imgs = ReportImgProvider(session)
        train_imgs = imgs.get({'task': tp.by_id(tasks['train_a'][0]).id})
        assert train_imgs['total'] > 0
        valid_imgs = imgs.get({'task': single.id})
        assert valid_imgs['total'] > 0
