"""Self-healing serving fleet (server/fleet.py + server/gateway.py).

Three layers of coverage, cheapest first:

- pure router logic (circuit breaker, hedge budget, rolling-SLO
  window) with injected clocks — no sockets;
- the gateway's proxy path against stub HTTP backends — failover,
  hedge-budget exhaustion, shed-rate accounting, probe exemption;
- the reconciler inside a REAL SupervisorBuilder tick against a
  sandboxed DB — desired-count spawn through the normal placement
  path, probe-failure classification → kill → exactly-once respawn
  with computer exclusion, heartbeat-silence verdicts, the rolling
  swap state machine (warm → flip → drain, and warmup-timeout
  rollback), and the ``serve_replica`` executor running a real
  ModelServer end to end.

The full chaos acceptance (kill 1 of 3 replica SUBPROCESSES mid-load,
zero non-429 failures) runs jax-free in scripts/chaos_smoke.py.
"""

import datetime
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from mlcomp_tpu import TOKEN
from mlcomp_tpu.db.enums import TaskStatus
from mlcomp_tpu.db.models import Computer
from mlcomp_tpu.db.providers import (
    ComputerProvider, DockerProvider, FleetProvider, QueueProvider,
    ReplicaProvider, TaskProvider,
)
from mlcomp_tpu.server.fleet import (
    FleetConfig, create_fleet, start_swap, stop_fleet,
)
from mlcomp_tpu.server.gateway import (
    CircuitBreaker, FleetGateway, HedgeBudget, PROBE_HEADER, RollingSlo,
)
from mlcomp_tpu.utils.io import yaml_load
from mlcomp_tpu.utils.misc import now


# ---------------------------------------------------------------- helpers
def add_computer(session, name, heartbeat=True):
    ComputerProvider(session).create_or_update(
        Computer(name=name, cores=8, cpu=16, memory=64,
                 ip='127.0.0.1', can_process_tasks=True), 'name')
    if heartbeat:
        DockerProvider(session).heartbeat(name, 'default')


def make_supervisor(session, health=None, **fleet_kw):
    """SupervisorBuilder with an injectable probe: ``health`` maps
    url -> bool (default healthy)."""
    from mlcomp_tpu.server.supervisor import SupervisorBuilder
    health = health if health is not None else {}
    cfg = FleetConfig(probe_interval_s=0.0, unhealthy_after=2,
                      **fleet_kw)
    return SupervisorBuilder(
        session=session, fleet_config=cfg,
        fleet_probe=lambda url: health.get(url, True)), health


def bring_up(session, fleet_id):
    """Play the worker's part for every starting replica: claim the
    dispatch, mark InProgress, bind a (fake) endpoint."""
    rp, tp, qp = (ReplicaProvider(session), TaskProvider(session),
                  QueueProvider(session))
    for replica in rp.of_fleet(fleet_id, states=('starting',)):
        task = tp.by_id(replica.task)
        if task is None or task.status != int(TaskStatus.Queued):
            continue
        qp.claim([f'{task.computer_assigned}_default'],
                 f'{task.computer_assigned}:0')
        tp.change_status(task, TaskStatus.InProgress)
        rp.mark_endpoint(replica.id, task.computer_assigned,
                         9000 + replica.id,
                         f'http://127.0.0.1:{9000 + replica.id}')


def expire_probes(session):
    session.execute(
        'UPDATE serve_replica SET last_probe=?',
        (now() - datetime.timedelta(seconds=3600),))


# --------------------------------------------------------- router logic
class TestCircuitBreaker:
    def test_open_half_open_close_cycle(self):
        clock = [0.0]
        cb = CircuitBreaker(failure_threshold=3, cooldown_s=10.0,
                            clock=lambda: clock[0])
        assert cb.state == 'closed' and cb.allow()
        for _ in range(3):
            cb.record_failure()
        assert cb.state == 'open'
        assert not cb.allow()               # cooling down
        clock[0] = 10.1
        assert cb.allow()                   # the half-open trial
        assert cb.state == 'half_open'
        assert not cb.allow()               # one trial at a time
        cb.record_success()
        assert cb.state == 'closed' and cb.allow()

    def test_half_open_failure_reopens(self):
        clock = [0.0]
        cb = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                            clock=lambda: clock[0])
        cb.record_failure()
        assert cb.state == 'open'
        clock[0] = 5.1
        assert cb.allow()
        cb.record_failure()                 # trial failed
        assert cb.state == 'open'
        assert not cb.allow()               # cooldown restarted
        clock[0] = 10.2
        assert cb.allow()

    def test_success_resets_failure_streak(self):
        cb = CircuitBreaker(failure_threshold=3)
        cb.record_failure()
        cb.record_failure()
        cb.record_success()
        cb.record_failure()
        cb.record_failure()
        assert cb.state == 'closed'         # never 3 consecutive


class TestHedgeBudget:
    def test_exhaustion_and_earn_back(self):
        budget = HedgeBudget(ratio=0.5, burst=2.0)
        assert budget.try_spend() and budget.try_spend()
        assert not budget.try_spend()       # drained
        budget.note_request()               # +0.5
        assert not budget.try_spend()
        budget.note_request()               # 1.0 — one hedge earned
        assert budget.try_spend()
        assert not budget.try_spend()

    def test_burst_cap(self):
        budget = HedgeBudget(ratio=1.0, burst=3.0)
        for _ in range(100):
            budget.note_request()
        spent = sum(1 for _ in range(10) if budget.try_spend())
        assert spent == 3


class TestRollingSlo:
    def test_min_samples_gate(self):
        slo = RollingSlo(10.0, min_samples=5)
        for _ in range(4):
            slo.observe(100.0)
        assert slo.p99() is None and not slo.over_slo()
        slo.observe(100.0)
        assert slo.over_slo()

    def test_age_expiry_releases_shedding(self):
        """The 100%-shed deadlock guard: a fully-shed (quiet) window
        must drain by AGE so admission resumes as a recovery probe."""
        clock = [0.0]
        slo = RollingSlo(10.0, min_samples=5, max_age_s=10.0,
                         clock=lambda: clock[0])
        for _ in range(10):
            slo.observe(100.0)
        assert slo.over_slo()
        clock[0] = 10.1                     # everything expires
        assert slo.p99() is None and not slo.over_slo()

    def test_p99_tracks_tail(self):
        slo = RollingSlo(50.0, min_samples=10)
        for ms in [1.0] * 99 + [500.0]:
            slo.observe(ms)
        assert slo.p99() == 500.0


# ------------------------------------------------------- gateway proxy
def make_stub(behavior):
    """Stub backend; ``behavior`` is a mutable dict:
    status (int), delay_s, count (incremented per predict)."""
    class Stub(BaseHTTPRequestHandler):
        protocol_version = 'HTTP/1.1'

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get('Content-Length', 0))
            self.rfile.read(n)
            behavior['count'] = behavior.get('count', 0) + 1
            if behavior.get('delay_s'):
                time.sleep(behavior['delay_s'])
            status = behavior.get('status', 200)
            blob = json.dumps(
                {'y': [behavior['port']], 'status': status}).encode()
            self.send_response(status)
            self.send_header('Content-Length', str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)
    srv = ThreadingHTTPServer(('127.0.0.1', 0), Stub)
    behavior['port'] = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


@pytest.fixture()
def stub_pair():
    b1, b2 = {}, {}
    s1, s2 = make_stub(b1), make_stub(b2)
    yield (b1, b2)
    s1.shutdown()
    s2.shutdown()


def gw_post(gw, path='/predict/m', body=b'{"x": [[1]]}', headers=None):
    req = urllib.request.Request(
        f'http://127.0.0.1:{gw.port}{path}', data=body,
        headers={'Authorization': TOKEN, **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, (json.loads(payload) if payload else {}), \
            e.headers


class TestGatewayRouting:
    def _gateway(self, behaviors, **kw):
        gw = FleetGateway(port=0, **kw)
        gw.set_fleet('m', 1,
                     [f'http://127.0.0.1:{b["port"]}'
                      for b in behaviors], slo_p99_ms=None)
        gw.start_background()
        return gw

    def test_round_robin(self, stub_pair):
        b1, b2 = stub_pair
        gw = self._gateway([b1, b2])
        try:
            seen = {gw_post(gw)[1]['y'][0] for _ in range(4)}
            assert seen == {b1['port'], b2['port']}
        finally:
            gw.shutdown()

    def test_unauthorized(self, stub_pair):
        b1, b2 = stub_pair
        gw = self._gateway([b1, b2])
        try:
            req = urllib.request.Request(
                f'http://127.0.0.1:{gw.port}/predict/m', data=b'{}',
                headers={'Authorization': 'wrong'})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 401
        finally:
            gw.shutdown()

    def test_failover_on_5xx_and_breaker_opens(self, stub_pair):
        b1, b2 = stub_pair
        gw = self._gateway([b1, b2], hedge_ratio=1.0,
                           breaker_kw={'failure_threshold': 2,
                                       'cooldown_s': 60.0})
        try:
            b1['status'] = 500
            codes = [gw_post(gw)[0] for _ in range(6)]
            assert codes == [200] * 6       # hedges absorbed the 500s
            snap = gw.route('m').snapshot()
            sick = [b for b in snap['backends']
                    if b['url'].endswith(str(b1['port']))][0]
            assert sick['circuit'] == 'open'
            assert snap['failovers'] >= 1
            # with the circuit open, routing goes healthy-only: the
            # sick backend sees no more traffic
            before = b1.get('count', 0)
            for _ in range(4):
                assert gw_post(gw)[0] == 200
            assert b1.get('count', 0) == before
        finally:
            gw.shutdown()

    def test_hedge_budget_exhaustion_surfaces_errors(self, stub_pair):
        b1, b2 = stub_pair
        # both backends sick and a tiny budget: once spent, the
        # replica's own verdict surfaces instead of a retry storm
        b1['status'] = 500
        b2['status'] = 500
        gw = self._gateway([b1, b2], hedge_ratio=0.0,
                           breaker_kw={'failure_threshold': 100})
        try:
            gw.route('m').hedge.tokens = 1.0
            codes = [gw_post(gw)[0] for _ in range(4)]
            assert codes == [500] * 4
            snap = gw.route('m').snapshot()
            assert snap['hedges'] == 1      # the one budgeted hedge
            assert snap['errors'] == 4
        finally:
            gw.shutdown()

    def test_replica_429_fails_over_without_breaker_penalty(
            self, stub_pair):
        b1, b2 = stub_pair
        b1['status'] = 429
        gw = self._gateway([b1, b2], hedge_ratio=1.0,
                           breaker_kw={'failure_threshold': 1})
        try:
            codes = [gw_post(gw)[0] for _ in range(4)]
            assert 200 in codes
            snap = gw.route('m').snapshot()
            sick = [b for b in snap['backends']
                    if b['url'].endswith(str(b1['port']))][0]
            assert sick['circuit'] == 'closed'   # busy, not broken
        finally:
            gw.shutdown()

    def test_client_4xx_passthrough_no_hedge(self, stub_pair):
        b1, b2 = stub_pair
        b1['status'] = 400
        b2['status'] = 400
        gw = self._gateway([b1, b2], hedge_ratio=1.0)
        try:
            code, _, _ = gw_post(gw)
            assert code == 400
            assert gw.route('m').snapshot()['hedges'] == 0
        finally:
            gw.shutdown()

    def test_no_backends_is_503_with_retry_after(self):
        gw = FleetGateway(port=0)
        gw.set_fleet('m', 1, [])
        gw.start_background()
        try:
            code, _, headers = gw_post(gw)
            assert code == 503
            assert headers.get('Retry-After') == '1'
        finally:
            gw.shutdown()

    def test_unknown_fleet_404_and_single_fleet_default(self,
                                                       stub_pair):
        b1, b2 = stub_pair
        gw = self._gateway([b1, b2])
        try:
            assert gw_post(gw, path='/predict/nope')[0] == 404
            assert gw_post(gw, path='/predict')[0] == 200
        finally:
            gw.shutdown()


class TestShedAccounting:
    def test_shed_rate_under_synthetic_overload(self, stub_pair):
        """Once the rolling p99 is over the SLO, new requests shed
        with 429 + Retry-After and the shed counter accounts for every
        one of them — while probe-marked requests pass."""
        b1, b2 = stub_pair
        gw = FleetGateway(port=0)
        route = gw.set_fleet(
            'm', 1, [f'http://127.0.0.1:{b1["port"]}',
                     f'http://127.0.0.1:{b2["port"]}'],
            slo_p99_ms=10.0)
        route.slo.min_samples = 5
        gw.start_background()
        try:
            # poison the window over the SLO (synthetic: no real load)
            for _ in range(10):
                route.slo.observe(100.0)
            codes = [gw_post(gw)[0] for _ in range(10)]
            assert codes == [429] * 10
            _, _, headers = gw_post(gw)
            assert headers.get('Retry-After') == '1'
            snap = route.snapshot()
            assert snap['shed'] == 11
            assert snap['requests'] == 11
            # health probes are never shed
            code, _, _ = gw_post(gw, headers={PROBE_HEADER: '1'})
            assert code == 200
            assert route.snapshot()['shed'] == 11
            # /metrics carries the shed counter
            from mlcomp_tpu.telemetry.export import parse_openmetrics
            doc = parse_openmetrics(gw.render_metrics())
            shed = doc['mlcomp_fleet_shed']['samples']
            assert shed[0][1] == {'fleet': 'm'} and shed[0][2] == 11
        finally:
            gw.shutdown()

    def test_queue_limit_backstop(self, stub_pair):
        b1, b2 = stub_pair
        b1['delay_s'] = 0.5
        b2['delay_s'] = 0.5
        gw = FleetGateway(port=0)
        route = gw.set_fleet(
            'm', 1, [f'http://127.0.0.1:{b1["port"]}',
                     f'http://127.0.0.1:{b2["port"]}'],
            slo_p99_ms=None, max_pending=1)
        gw.start_background()
        try:
            codes = []
            lock = threading.Lock()

            def client():
                code = gw_post(gw)[0]
                with lock:
                    codes.append(code)
            threads = [threading.Thread(target=client)
                       for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert 200 in codes and 429 in codes, codes
        finally:
            gw.shutdown()


class TestAtomicAdmission:
    """Regression for the admission check-then-act race the
    concurrency lint flags as cc-lockset: ``inflight >= max_pending``
    was read OUTSIDE route.lock, then incremented under it — a
    concurrent burst could all pass the check together and overshoot
    the bound. ``route.admit()`` now does both under one lock hold, so
    a racing burst admits EXACTLY max_pending whatever the
    interleaving (admitted requests hold their slot until release —
    no timing in the assertion)."""

    def _route(self, max_pending):
        from mlcomp_tpu.server.gateway import _FleetRoute
        return _FleetRoute('m', slo_p99_ms=None,
                           max_pending=max_pending)

    def test_burst_never_overshoots_max_pending(self):
        route = self._route(4)
        n = 16
        barrier = threading.Barrier(n)
        verdicts = []
        lock = threading.Lock()

        def client():
            barrier.wait()
            ok = route.admit()
            with lock:
                verdicts.append(ok)

        threads = [threading.Thread(target=client) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert sum(verdicts) == 4            # exactly the bound
        assert route.inflight == 4
        snap = route.snapshot()
        assert snap['shed'] == n - 4
        assert snap['requests'] == n
        for _ in range(4):
            route.release()
        assert route.inflight == 0
        # slots freed: admission resumes
        assert route.admit() is True

    def test_probe_bypasses_a_full_queue(self):
        route = self._route(1)
        assert route.admit() is True
        assert route.admit() is False        # full: shed
        assert route.admit(probe=True) is True   # probes never shed
        assert route.inflight == 2
        assert route.snapshot()['shed'] == 1


class TestStartSwapRace:
    """Regression for the reconciler-transition finding
    (db-naked-transition on start_swap): the old read-check-write let
    two operators holding the SAME stale fleet row both pass the
    'already swapping' check and stage clashing target generations.
    The conditional UPDATE (WHERE status='active') picks exactly one
    winner; the loser gets the ValueError the stale check used to
    give only by luck. Deterministic: both rows are read before
    either writes — the exact lost-update interleaving."""

    def test_second_stale_swapper_loses(self, session):
        create_fleet(session, 'swapf', 'model_v1', desired=1)
        fp = FleetProvider(session)
        stale_a = fp.by_name('swapf')
        stale_b = fp.by_name('swapf')        # both read status=active
        start_swap(session, stale_a, 'model_v2')
        with pytest.raises(ValueError, match='swapping'):
            start_swap(session, stale_b, 'model_v3')
        row = fp.by_name('swapf')
        assert row.status == 'swapping'
        assert row.target_model == 'model_v2'     # winner's staging
        assert row.target_generation == 2         # not double-bumped

    def test_stale_swap_after_completed_swap_refused(self, session):
        """status='active' alone is not enough of a guard: after an
        intervening COMPLETED swap the fleet is active again at
        generation+1, and a stale caller's target (stale_gen + 1)
        would collide with the LIVE generation. The WHERE pins the
        generation the caller read, so the stale request loses."""
        create_fleet(session, 'genf', 'model_v1', desired=1)
        fp = FleetProvider(session)
        stale = fp.by_name('genf')           # generation 1, active
        # a full swap completes meanwhile: generation 2, active again
        session.execute(
            "UPDATE serve_fleet SET generation=2, model='model_v2' "
            "WHERE name='genf'")
        with pytest.raises(ValueError, match='moved to generation 2'):
            start_swap(session, stale, 'model_v3')
        row = fp.by_name('genf')
        assert row.status == 'active'
        assert row.target_generation is None     # nothing staged
        # a fresh read swaps cleanly to generation 3
        start_swap(session, fp.by_name('genf'), 'model_v3')
        row = fp.by_name('genf')
        assert row.target_generation == 3

    def test_swap_on_stopped_fleet_refused(self, session):
        fleet = create_fleet(session, 'stopf', 'model_v1', desired=0)
        stop_fleet(session, fleet)
        stale = FleetProvider(session).by_name('stopf')
        with pytest.raises(ValueError, match='stopped'):
            start_swap(session, stale, 'model_v2')
        row = FleetProvider(session).by_name('stopf')
        assert row.status == 'stopped' and row.target_model is None


# ----------------------------------------------------------- reconciler
class TestReconciler:
    def test_spawn_to_desired_through_placement(self, session):
        for host in ('h1', 'h2', 'h3'):
            add_computer(session, host)
        fleet = create_fleet(session, 'f', 'm', desired=3)
        sup, _ = make_supervisor(session)
        sup.build()
        rp, tp = ReplicaProvider(session), TaskProvider(session)
        replicas = rp.of_fleet(fleet.id)
        assert len(replicas) == 3
        tasks = [tp.by_id(r.task) for r in replicas]
        assert all(t.status == int(TaskStatus.Queued) for t in tasks)
        assert len({t.computer_assigned for t in tasks}) == 3
        info = yaml_load(tasks[0].additional_info)
        assert info['serve']['fleet_name'] == 'f'
        assert info['serve']['model'] == 'm'
        # steady state: no spawn storm
        sup.build()
        assert len(rp.of_fleet(fleet.id)) == 3

    def test_probe_failure_respawns_exactly_once_excluding_host(
            self, session):
        for host in ('h1', 'h2', 'h3'):
            add_computer(session, host)
        fleet = create_fleet(session, 'f', 'm', desired=2)
        health = {}
        sup, health = make_supervisor(session, health)
        sup.build()
        bring_up(session, fleet.id)
        sup.build()
        rp, tp = ReplicaProvider(session), TaskProvider(session)
        assert all(r.state == 'healthy'
                   for r in rp.of_fleet(fleet.id))
        victim = rp.of_fleet(fleet.id)[0]
        health[victim.url] = False
        for _ in range(3):
            expire_probes(session)
            sup.build()
        rows = rp.of_fleet(fleet.id)
        dead = next(r for r in rows if r.id == victim.id)
        assert dead.state == 'dead'
        assert dead.failure_reason == 'replica-unhealthy'
        vt = tp.by_id(victim.task)
        assert vt.status == int(TaskStatus.Failed)
        assert vt.failure_reason == 'replica-unhealthy'
        spawned = [r for r in rows if r.respawned_from == victim.id]
        assert len(spawned) == 1
        nt = tp.by_id(spawned[0].task)
        info = yaml_load(nt.additional_info)
        assert info['retry_exclude'] == [vt.computer_assigned]
        assert nt.computer_assigned != vt.computer_assigned
        # exactly once: more ticks mint nothing new
        for _ in range(3):
            expire_probes(session)
            sup.build()
        assert len(rp.of_fleet(fleet.id)) == 3
        # the respawn event is on /metrics
        from mlcomp_tpu.telemetry.export import (
            parse_openmetrics, render_server_metrics,
        )
        doc = parse_openmetrics(render_server_metrics(session))
        assert any(l.get('fleet') == 'f'
                   and l.get('reason') == 'replica-unhealthy'
                   for _, l, _ in
                   doc['mlcomp_fleet_respawns']['samples'])

    def test_bound_but_never_healthy_replica_is_replaced(self, session):
        """A replica that binds its endpoint but NEVER answers a
        healthy probe (sick export) must still be classified and
        replaced — not parked in 'starting' below desired capacity."""
        add_computer(session, 'h1')
        add_computer(session, 'h2')
        fleet = create_fleet(session, 'f', 'm', desired=1)
        health = {}
        sup, health = make_supervisor(session, health)
        sup.build()
        bring_up(session, fleet.id)
        rp = ReplicaProvider(session)
        replica = rp.of_fleet(fleet.id)[0]
        health[replica.url] = False         # never healthy
        for _ in range(4):
            expire_probes(session)
            sup.build()
        rows = rp.of_fleet(fleet.id)
        dead = next(r for r in rows if r.id == replica.id)
        assert dead.state == 'dead'
        assert dead.failure_reason == 'replica-unhealthy'
        assert any(r.respawned_from == replica.id for r in rows)

    def test_heartbeat_silence_is_worker_lost(self, session):
        add_computer(session, 'h1')
        add_computer(session, 'h2')
        fleet = create_fleet(session, 'f', 'm', desired=1)
        sup, _ = make_supervisor(session, replica_silence_s=60)
        sup.build()
        bring_up(session, fleet.id)
        sup.build()
        rp, tp = ReplicaProvider(session), TaskProvider(session)
        replica = rp.of_fleet(fleet.id)[0]
        session.execute(
            'UPDATE task SET last_activity=? WHERE id=?',
            (now() - datetime.timedelta(seconds=300), replica.task))
        sup.build()
        replica = rp.by_id(replica.id)
        assert replica.state == 'dead'
        assert replica.failure_reason == 'worker-lost'
        assert tp.by_id(replica.task).failure_reason == 'worker-lost'

    def test_task_verdict_absorbed(self, session):
        """A replica whose task the LEASE/watchdog machinery failed
        inherits that verdict — no probe needed."""
        add_computer(session, 'h1')
        add_computer(session, 'h2')
        fleet = create_fleet(session, 'f', 'm', desired=1)
        sup, _ = make_supervisor(session)
        sup.build()
        rp, tp = ReplicaProvider(session), TaskProvider(session)
        replica = rp.of_fleet(fleet.id)[0]
        tp.fail_with_reason(tp.by_id(replica.task), 'lease-expired')
        sup.build()
        rows = rp.of_fleet(fleet.id)
        dead = next(r for r in rows if r.id == replica.id)
        assert dead.state == 'dead'
        assert dead.failure_reason == 'lease-expired'
        assert len(rows) == 2               # replacement minted

    def test_scale_down_is_not_a_respawn_storm(self, session):
        add_computer(session, 'h1')
        fleet = create_fleet(session, 'f', 'm', desired=2, cores=1)
        sup, _ = make_supervisor(session)
        sup.build()
        fp = FleetProvider(session)
        fleet = fp.by_name('f')
        fleet.desired = 0
        fp.touch(fleet, ['desired'])
        sup.build()
        # desired 0: nothing new minted (live replicas are retired by
        # stop/swap flows, not the count reconciler)
        assert len(ReplicaProvider(session).of_fleet(fleet.id)) == 2

    def test_stop_fleet_kills_replicas(self, session):
        add_computer(session, 'h1')
        fleet = create_fleet(session, 'f', 'm', desired=2)
        sup, _ = make_supervisor(session)
        sup.build()
        stop_fleet(session, FleetProvider(session).by_name('f'))
        assert FleetProvider(session).by_name('f').status == 'stopped'
        rp = ReplicaProvider(session)
        assert all(r.state == 'dead' for r in rp.of_fleet(fleet.id))
        sup.build()                         # stopped: not reconciled
        assert all(r.state == 'dead' for r in rp.of_fleet(fleet.id))


class TestRollingSwap:
    def _warm_fleet(self, session, desired=2):
        for host in ('h1', 'h2'):
            add_computer(session, host)
        fleet = create_fleet(session, 'f', 'm_v1', desired=desired)
        sup, health = make_supervisor(session, drain_grace_s=0.0)
        sup.build()
        bring_up(session, fleet.id)
        sup.build()
        return fleet, sup, health

    def test_flip_after_warmup_then_drain(self, session):
        fleet, sup, _ = self._warm_fleet(session)
        fp, rp, tp = (FleetProvider(session), ReplicaProvider(session),
                      TaskProvider(session))
        start_swap(session, fp.by_name('f'), 'm_v2')
        sup.build()                         # stage generation 2
        gen2 = rp.of_fleet(fleet.id, generation=2)
        assert len(gen2) == 2
        info = yaml_load(tp.by_id(gen2[0].task).additional_info)
        assert info['serve']['model'] == 'm_v2'
        # generation 1 still routed while 2 warms
        assert fp.by_name('f').generation == 1
        expire_probes(session)
        bring_up(session, fleet.id)
        sup.build()                         # gen2 healthy -> flip
        fleet_row = fp.by_name('f')
        assert fleet_row.generation == 2
        assert fleet_row.model == 'm_v2'
        assert fleet_row.status == 'active'
        assert fleet_row.target_generation is None
        g1 = rp.of_fleet(fleet.id, generation=1)
        assert all(r.state == 'draining' for r in g1)
        sup.build()                         # drain grace 0: retire
        g1 = rp.of_fleet(fleet.id, generation=1)
        statuses = [tp.by_id(r.task).status for r in g1]
        assert all(s >= int(TaskStatus.Failed) for s in statuses)
        sup.build()
        assert all(r.state == 'dead'
                   for r in rp.of_fleet(fleet.id, generation=1))
        # swap event exported
        from mlcomp_tpu.telemetry.export import (
            parse_openmetrics, render_server_metrics,
        )
        doc = parse_openmetrics(render_server_metrics(session))
        assert any(l.get('outcome') == 'completed'
                   for _, l, _ in doc['mlcomp_fleet_swaps']['samples'])

    def test_failed_warmup_rolls_back(self, session):
        fleet, sup, health = self._warm_fleet(session)
        fp, rp = FleetProvider(session), ReplicaProvider(session)
        start_swap(session, fp.by_name('f'), 'm_v2')
        sup.build()
        for replica in rp.of_fleet(fleet.id, generation=2):
            health[f'http://127.0.0.1:{9000 + replica.id}'] = False
        session.execute(
            'UPDATE serve_fleet SET swap_started=? WHERE id=?',
            (now() - datetime.timedelta(seconds=3600), fleet.id))
        sup.build()
        fleet_row = fp.by_name('f')
        assert fleet_row.generation == 1    # never flipped
        assert fleet_row.model == 'm_v1'
        assert fleet_row.status == 'active'
        assert fleet_row.target_generation is None
        assert all(r.state == 'dead'
                   and r.failure_reason == 'swap-rollback'
                   for r in rp.of_fleet(fleet.id, generation=2))
        from mlcomp_tpu.db.providers import AlertProvider
        alerts = AlertProvider(session).get(status='open',
                                            rule='swap-rollback')
        assert alerts and alerts[0].severity == 'critical'
        # generation 1 keeps serving and is still reconciled
        assert len(rp.live(fleet.id, 1)) == 2

    def test_double_swap_rejected(self, session):
        fleet, sup, _ = self._warm_fleet(session)
        fp = FleetProvider(session)
        start_swap(session, fp.by_name('f'), 'm_v2')
        with pytest.raises(ValueError, match='already swapping'):
            start_swap(session, fp.by_name('f'), 'm_v3')


class TestGatewayDbRefresh:
    def test_routes_follow_active_generation(self, session, stub_pair):
        b1, b2 = stub_pair
        add_computer(session, 'h1')
        add_computer(session, 'h2')
        fleet = create_fleet(session, 'f', 'm', desired=1)
        sup, _ = make_supervisor(session)
        sup.build()
        rp = ReplicaProvider(session)
        replica = rp.of_fleet(fleet.id)[0]
        bring_up(session, fleet.id)
        rp.mark_endpoint(replica.id, 'h1', b1['port'],
                         f'http://127.0.0.1:{b1["port"]}')
        sup.build()
        gw = FleetGateway(port=0, session=session, refresh_s=3600)
        gw.start_background()
        gw.refresh_from_db()
        try:
            code, body, _ = gw_post(gw, path='/predict/f')
            assert code == 200 and body['y'] == [b1['port']]
            # flip the healthy endpoint to the second stub (a new
            # generation in miniature) and refresh
            session.execute(
                'UPDATE serve_replica SET url=? WHERE id=?',
                (f'http://127.0.0.1:{b2["port"]}', replica.id))
            gw.refresh_from_db()
            code, body, _ = gw_post(gw, path='/predict/f')
            assert code == 200 and body['y'] == [b2['port']]
            # stopped fleet drops out of the routing table
            stop_fleet(session, FleetProvider(session).by_name('f'))
            gw.refresh_from_db()
            assert gw_post(gw, path='/predict/f')[0] == 404
        finally:
            gw.shutdown()


# ------------------------------------------------- serve_replica executor
@pytest.mark.slow
class TestServeReplicaExecutor:
    def test_executor_serves_and_reports_endpoint(self, session,
                                                  tmp_path):
        import numpy as np
        import jax
        from mlcomp_tpu.db.models import ServeFleet, ServeReplica, Task
        from mlcomp_tpu.models import create_model
        from mlcomp_tpu.train.export import export_model
        from mlcomp_tpu.worker.executors import Executor

        spec = {'name': 'mlp', 'num_classes': 3, 'hidden': [8],
                'dtype': 'float32'}
        model = create_model(**spec)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 4, 4, 1), np.float32),
                               train=False)
        path = export_model(
            str(tmp_path / 'exp'), variables['params'], spec,
            meta={'input_shape': [4, 4, 1]})
        fp, rp, tp = (FleetProvider(session), ReplicaProvider(session),
                      TaskProvider(session))
        fleet = ServeFleet(name='exec', model=path, desired=1,
                           created=now())
        fp.add(fleet)
        replica = ServeReplica(fleet=fleet.id, generation=1,
                               state='starting', created=now())
        rp.add(replica)
        task = Task(name='serve_exec', executor='serve_replica',
                    status=int(TaskStatus.InProgress),
                    last_activity=now())
        tp.add(task)
        cls = Executor.get('serve_replica')
        ex = cls()
        ex.additional_info = {'serve': {
            'fleet': fleet.id, 'fleet_name': 'exec',
            'replica': replica.id, 'generation': 1,
            'model': path, 'batch_size': 8}}
        ex.session = session
        ex.task = task
        ex.beat_interval_s = 0.1
        result = {}

        def run():
            result['out'] = ex.work()
        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            row = rp.by_id(replica.id)
            if row.url:
                break
            time.sleep(0.05)
        row = rp.by_id(replica.id)
        assert row.url and row.port
        # the replica answers the fleet probe contract AND predicts
        from mlcomp_tpu.server.fleet import http_probe
        assert http_probe(row.url) is True
        req = urllib.request.Request(
            row.url + '/predict',
            data=json.dumps(
                {'x': np.zeros((2, 4, 4, 1)).tolist()}).encode(),
            headers={'Authorization': TOKEN})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert np.asarray(out['y']).shape == (2, 3)
        # the beat touches last_activity (the silence horizon input)
        before = tp.by_id(task.id).last_activity
        time.sleep(0.3)
        assert tp.by_id(task.id).last_activity >= before
        ex.server.shutdown()
        thread.join(timeout=10)
        assert result['out']['replica'] == replica.id
        assert result['out']['requests'] >= 1


# ------------------------------------------------------- migration/API
class TestFleetDbAndApi:
    def test_v8_db_upgrades_in_place(self, tmp_path):
        """A pre-fleet DB (migrations rolled to v8) gains the v9
        tables on migrate() without touching existing rows."""
        import sqlite3
        from mlcomp_tpu.db.core import Session
        from mlcomp_tpu.db.migration import MIGRATIONS, migrate
        db = tmp_path / 'old.db'
        session = Session(f'sqlite:///{db}', key='fleet_v8_upgrade')
        session.execute(
            'CREATE TABLE IF NOT EXISTS migration_version '
            '(version INTEGER)')
        for i, fn in enumerate(MIGRATIONS[:8], start=1):
            fn(session)
            session.execute(
                'INSERT INTO migration_version (version) VALUES (?)',
                (i,))
        session.execute(
            "INSERT INTO task (name, executor, status) "
            "VALUES ('old', 'e', 0)")
        migrate(session)
        names = {r['name'] for r in session.query(
            "SELECT name FROM sqlite_master WHERE type='table'")}
        assert {'serve_fleet', 'serve_replica'} <= names
        assert session.query_one(
            'SELECT COUNT(*) AS c FROM task')['c'] == 1

    def test_api_fleet_lifecycle(self, session):
        from mlcomp_tpu.server.api import (
            api_fleet_create, api_fleet_scale, api_fleet_stop,
            api_fleet_swap, api_fleets,
        )
        res = api_fleet_create(
            {'name': 'apif', 'model': 'm', 'desired': 2,
             'slo_p99_ms': 100}, session)
        assert res['success']
        listing = api_fleets({}, session)['data']
        assert listing[0]['name'] == 'apif'
        assert listing[0]['slo_p99_ms'] == 100.0
        api_fleet_scale({'name': 'apif', 'desired': 4}, session)
        api_fleet_swap({'name': 'apif', 'model': 'm2'}, session)
        listing = api_fleets({}, session)['data'][0]
        assert listing['desired'] == 4
        assert listing['status'] == 'swapping'
        assert listing['target_model'] == 'm2'
        from mlcomp_tpu.server.api import ApiError
        with pytest.raises(ApiError):       # duplicate name
            api_fleet_create({'name': 'apif', 'model': 'm'}, session)
        with pytest.raises(ApiError):       # double swap
            api_fleet_swap({'name': 'apif', 'model': 'm3'}, session)
        api_fleet_stop({'name': 'apif'}, session)
        assert api_fleets({}, session)['data'] == []
        assert api_fleets({'all': True}, session)['data'][0][
            'status'] == 'stopped'
