"""Multi-host distributed training, end to end.

The VERDICT-round-1 gap: the supervisor manufactured ``distr_info`` that
nothing consumed. These tests prove the full loop: supervisor fan-out →
service tasks on two (emulated) computers → two real OS worker processes →
``jax.distributed.initialize`` over a localhost coordinator → one global
8-device mesh (2 processes × 4 CPU devices) → gradient psum across the
process boundary → loss identical to a single-process 8-device run.

Reference counterpart: supervisor.py:228-313 (service-task fan-out) +
catalyst.py:195-207 (env contract consumption by torch.distributed).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mlcomp_tpu.db.enums import TaskStatus, TaskType
from mlcomp_tpu.db.models import Computer
from mlcomp_tpu.db.providers import (
    ComputerProvider, DockerProvider, ReportSeriesProvider, TaskProvider,
)
from mlcomp_tpu.server.create_dags.standard import dag_standard
from mlcomp_tpu.server.supervisor import SupervisorBuilder

TRAIN_SPEC = {
    'type': 'jax_train',
    'model': {'name': 'mlp', 'hidden': [32], 'num_classes': 10},
    'dataset': {'name': 'synthetic_images', 'n_train': 256,
                'n_valid': 64, 'image_size': 8},
    'loss': 'softmax_ce',
    'batch_size': 32,
    'epochs': 2,
    'mesh': {'dp': -1},
    'seed': 7,
}


def _submit_distributed_dag(session, tmp_path):
    exp = tmp_path / 'exp'
    exp.mkdir(exist_ok=True)
    config = {
        'info': {'name': 'dist_dag', 'project': 'p_dist'},
        'executors': {
            'train': dict(TRAIN_SPEC, cores=8, single_node=False,
                          distr=True),
        },
    }
    dag, tasks = dag_standard(session, config, upload_folder=str(exp))
    return tasks['train'][0]


def _add_computer(session, name):
    ComputerProvider(session).create_or_update(
        Computer(name=name, cores=4, cpu=8, memory=32, ip='127.0.0.1',
                 can_process_tasks=True), 'name')
    DockerProvider(session).heartbeat(name, 'default')


def _worker_env(host):
    import mlcomp_tpu
    env = dict(os.environ)
    env.update({
        'MLCOMP_TPU_ROOT': mlcomp_tpu.ROOT_FOLDER,
        'MLCOMP_HOSTNAME': host,
        'JAX_PLATFORMS': 'cpu',
        'XLA_FLAGS': '--xla_force_host_platform_device_count=4',
        'MLCOMP_TPU_CORES': '4',
    })
    env.pop('MLCOMP_TPU_TEST', None)  # subprocess must NOT wipe the root
    env.pop('PYTEST_XDIST_WORKER', None)
    return env


def _run_baseline(session, tmp_path):
    """Same training spec, single process, 8 local devices."""
    from mlcomp_tpu.utils.config import Config
    from mlcomp_tpu.worker.executors import Executor

    class _NullStep:
        def start(self, *a, **k):
            pass

        def end_all(self):
            pass

        def info(self, *a):
            pass

        def debug(self, *a):
            pass

        def error(self, *a):
            pass

    config = Config({'executors': {'train': dict(TRAIN_SPEC)}})
    executor = Executor.from_config('train', config, session=None)
    executor.checkpoint_dir = str(tmp_path / 'baseline_ck')
    executor.step = _NullStep()
    result = executor.work()
    return result


@pytest.mark.slow
def test_two_process_fanout_matches_single_process(session, tmp_path):
    task_id = _submit_distributed_dag(session, tmp_path)
    _add_computer(session, 'hosta')
    _add_computer(session, 'hostb')

    sup = SupervisorBuilder(session=session)
    sup.build()
    tp = TaskProvider(session)
    children = tp.children(task_id)
    assert len(children) == 2, sup.aux
    for child in children:
        assert child.type == int(TaskType.Service)

    # two real worker daemons, one per emulated computer
    procs = [
        subprocess.Popen(
            [sys.executable, '-m', 'mlcomp_tpu.worker', 'worker', '0'],
            env=_worker_env(host), cwd='/root/repo')
        for host in ('hosta', 'hostb')
    ]
    try:
        deadline = time.time() + 420
        while time.time() < deadline:
            sup.build()
            parent = tp.by_id(task_id)
            if parent.status >= int(TaskStatus.Failed):
                break
            time.sleep(1.0)
        parent = tp.by_id(task_id)
        children = tp.children(task_id)
        detail = [(c.id, TaskStatus(c.status).name, c.result)
                  for c in children]
        assert parent.status == int(TaskStatus.Success), detail
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)

    # rank 0 wrote per-epoch series; rank 1 was suppressed
    rank0 = min(c.id for c in children)
    rank1 = max(c.id for c in children)
    series = ReportSeriesProvider(session).by_task(rank0)
    losses = sorted(
        [(s.epoch, s.value) for s in series
         if s.name == 'loss' and s.part == 'train'])
    assert len(losses) == 2, series
    assert not ReportSeriesProvider(session).by_task(rank1)

    baseline = _run_baseline(session, tmp_path)
    # identical data order + init seed + 8-device dp mesh → losses match
    # the single-process run up to collective-reduction rounding
    result = json.loads(tp.by_id(rank0).result)
    assert result['best_score'] == pytest.approx(
        baseline['best_score'], abs=0.02)
    # and training actually learned across the process boundary
    assert losses[-1][1] < losses[0][1]


def _run_ranks(argv_for_rank, nprocs=2, timeout=300):
    """Spawn one CPU-mesh subprocess per rank (4 local devices each),
    kill leftovers on failure/timeout, return their outputs."""
    procs = []
    for rank in range(nprocs):
        env = dict(os.environ)
        env.update({
            'JAX_PLATFORMS': 'cpu',
            'XLA_FLAGS': '--xla_force_host_platform_device_count=4',
        })
        env.pop('MLCOMP_TPU_TEST', None)
        procs.append(subprocess.Popen(
            argv_for_rank(rank), env=env, cwd='/root/repo',
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert all(p.returncode == 0 for p in procs), outs
    return outs


@pytest.mark.slow
def test_dryrun_multiprocess_entry(tmp_path):
    """__graft_entry__.dryrun_multichip in 2-process mode: each rank runs
    the full sharded train step over the global 8-device mesh.

    slow: 2 subprocesses with a 300 s budget — a mark on the
    ``_run_ranks`` helper is inert (pytest only honours marks on
    collected tests), so it lives HERE to keep this out of the fast
    suite."""
    outs = _run_ranks(lambda rank: [
        sys.executable, '/root/repo/__graft_entry__.py', 'dryrun-mp',
        '8', str(rank), '2', '127.0.0.1:29655'])
    assert any('ok' in o for o in outs), outs


_CKPT_SCRIPT = r'''
import os, sys
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

rank, nprocs, folder, coord = (int(sys.argv[1]), int(sys.argv[2]),
                               sys.argv[3], sys.argv[4])
# CPU cross-process collectives (the write barriers) need an
# implementation selected before backend init — same assist
# parallel/distributed.py applies on the production path
jax.config.update('jax_cpu_collectives_implementation', 'gloo')
jax.distributed.initialize(coordinator_address=coord,
                           num_processes=nprocs, process_id=rank)
sys.path.insert(0, '/root/repo')
from mlcomp_tpu.train import ckpt_shard as cs
from mlcomp_tpu.train import checkpoint as ck

devs = np.array(jax.devices())


def state_on(mesh, spec_w, seed=0):
    w = jax.random.normal(jax.random.PRNGKey(seed), (64, 32),
                          jnp.float32)
    return {'params': {
        'w': jax.device_put(w, NamedSharding(mesh, spec_w)),
        'b': jax.device_put(jnp.arange(8, dtype=jnp.float32),
                            NamedSharding(mesh, P()))}}


mesh8 = Mesh(devs.reshape(8), ('fsdp',))
state = state_on(mesh8, P('fsdp', None), seed=3)
assert cs.state_needs_sharded_ckpt(state)
cs.save_checkpoint_sharded(folder, state, {'step': 4, 'score': 0.5},
                           best=True)

# restore onto the SAME mesh: each process reads only its own devices'
# slices (require_all=False tolerates per-host fragment visibility)
target = {'params': {
    'w': jax.device_put(np.zeros((64, 32), np.float32),
                        NamedSharding(mesh8, P('fsdp', None))),
    'b': jax.device_put(np.zeros(8, np.float32),
                        NamedSharding(mesh8, P()))}}
restored, meta = ck.restore_checkpoint(folder, target, kind='best')
assert meta['score'] == 0.5, meta


def check_shards(arr, want):
    # a cross-process global array cannot be fetched whole; compare
    # each process-local shard against the known host value
    for s in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(s.data),
                                      np.asarray(want)[s.index])


w_host = jax.device_get(jax.random.normal(
    jax.random.PRNGKey(3), (64, 32), jnp.float32))
check_shards(restored['params']['w'], w_host)

# RESHARD: restore onto a dp2 x fsdp4 mesh (different axis layout,
# same 2-process device set)
mesh24 = Mesh(devs.reshape(2, 4), ('dp', 'fsdp'))
target2 = {'params': {
    'w': jax.device_put(np.zeros((64, 32), np.float32),
                        NamedSharding(mesh24, P('fsdp', None))),
    'b': jax.device_put(np.zeros(8, np.float32),
                        NamedSharding(mesh24, P()))}}
restored2, _ = ck.restore_checkpoint(folder, target2)
check_shards(restored2['params']['w'], w_host)
print(f'rank {rank}: sharded multi-process ckpt ok', flush=True)
'''


@pytest.mark.slow
def test_two_process_sharded_checkpoint(tmp_path):
    """Sharded checkpoint across REAL process boundaries: both ranks
    write their own fragments + barriers, rank 0 the index; restore
    reads per-host slices and reshards onto a different mesh layout.
    (The training-loop save path is covered by
    test_two_process_fanout...; this pins the restore half.)"""
    script = tmp_path / 'ckpt_mp.py'
    script.write_text(_CKPT_SCRIPT)
    folder = tmp_path / 'ck'
    folder.mkdir()
    outs = _run_ranks(lambda rank: [
        sys.executable, str(script), str(rank), '2', str(folder),
        '127.0.0.1:29688'])
    assert all('ckpt ok' in o for o in outs), outs
    # both ranks' fragment files landed, one index, one leaves table
    names = sorted(os.listdir(folder / 'best'))
    frags = [n for n in names if n.startswith('shards-') and
             n.endswith('.json')]
    assert len(frags) == 2, names
    assert 'index.json' in names
