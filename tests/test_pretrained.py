"""Pretrained-weight loading (reference contrib/model/pretrained.py:6-59
head-swap semantics): ``model: {params_file: ...}`` seeds a fresh run
from a local export/npz; shape-mismatched heads re-initialize; a resumed
checkpoint wins over the file; fine-tuning beats from-scratch."""

import numpy as np
import pytest

from mlcomp_tpu.train import JaxTrain
from mlcomp_tpu.train.export import export_model, load_export
from mlcomp_tpu.train.pretrained import (
    apply_pretrained, load_pretrained_variables, merge_pretrained,
)

from test_train import run_executor


def _digits_spec(epochs, params_file=None, lr=3e-3, seed=0):
    model = {'name': 'mlp', 'num_classes': 10, 'hidden': [64],
             'dtype': 'float32'}
    if params_file:
        model['params_file'] = params_file
    return {
        'model': model,
        'dataset': {'name': 'digits'},
        'batch_size': 64,
        'seed': seed,
        'model_name': None,
        'stages': [{'name': 's1', 'epochs': epochs,
                    'optimizer': {'name': 'adam', 'lr': lr}}],
    }


class TestLoadMerge:
    def test_npz_roundtrip_with_and_without_params_prefix(self, tmp_path):
        w = np.arange(6, dtype=np.float32).reshape(2, 3)
        b = np.zeros(3, np.float32)
        p1 = str(tmp_path / 'a.npz')
        np.savez(p1, **{'params/Dense_0/kernel': w,
                        'params/Dense_0/bias': b})
        v1 = load_pretrained_variables(p1)
        p2 = str(tmp_path / 'b.npz')
        np.savez(p2, **{'Dense_0/kernel': w, 'Dense_0/bias': b})
        v2 = load_pretrained_variables(p2)
        for v in (v1, v2):
            assert np.array_equal(v['params']['Dense_0']['kernel'], w)
            assert np.array_equal(v['params']['Dense_0']['bias'], b)

    def test_missing_file_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_pretrained_variables(str(tmp_path / 'nope.msgpack'))
        with pytest.raises(FileNotFoundError):
            load_pretrained_variables(str(tmp_path / 'nope.npz'))

    def test_merge_head_swap(self):
        """Matching shapes load; the mismatched head keeps fresh init;
        missing paths keep fresh init."""
        init = {'params': {
            'body': {'kernel': np.zeros((4, 8), np.float32)},
            'head': {'kernel': np.zeros((8, 3), np.float32)},
            'extra': {'kernel': np.zeros((2, 2), np.float32)},
        }}
        loaded = {'params': {
            'body': {'kernel': np.ones((4, 8), np.float32)},
            'head': {'kernel': np.ones((8, 10), np.float32)},  # 10-class
        }}
        merged, summary = merge_pretrained(init, loaded)
        assert np.array_equal(merged['params']['body']['kernel'],
                              np.ones((4, 8)))
        assert np.array_equal(merged['params']['head']['kernel'],
                              np.zeros((8, 3)))
        assert np.array_equal(merged['params']['extra']['kernel'],
                              np.zeros((2, 2)))
        assert len(summary.loaded) == 1
        assert len(summary.reinit) == 1 and len(summary.missing) == 1
        assert 'head' in str(summary)

    def test_merge_zero_matches_raises(self):
        init = {'params': {'a': {'kernel': np.zeros((2, 2))}}}
        loaded = {'params': {'b': {'kernel': np.ones((2, 2))}}}
        with pytest.raises(ValueError, match='ZERO'):
            merge_pretrained(init, loaded)


class TestJaxTrainParamsFile:
    def test_finetune_beats_scratch_on_digits(self, tmp_path):
        """VERDICT r3 done-criterion: a JaxTrain run fine-tuning from a
        locally saved export beats from-scratch in fewer epochs."""
        pre = run_executor(_digits_spec(epochs=3),
                           str(tmp_path / 'ck_pre'))
        assert pre['best_score'] > 0.9
        # export the trained weights through the framework's own path
        from mlcomp_tpu.train.export import export_from_checkpoint
        export = export_from_checkpoint(
            str(tmp_path / 'ck_pre' / 'best.msgpack'),
            {'name': 'mlp', 'num_classes': 10, 'hidden': [64],
             'dtype': 'float32'},
            str(tmp_path / 'pre_export'))
        scratch = run_executor(_digits_spec(epochs=1),
                               str(tmp_path / 'ck_scratch'))
        tuned = run_executor(_digits_spec(epochs=1, params_file=export),
                             str(tmp_path / 'ck_tuned'))
        assert tuned['best_score'] > scratch['best_score']
        assert tuned['best_score'] >= pre['best_score'] - 0.02

    def test_head_swap_via_executor(self, tmp_path):
        """A 10-class export seeds a 4-class model: hidden layers load,
        head re-initializes, training still works."""
        run_executor(_digits_spec(epochs=1), str(tmp_path / 'ck_pre'))
        from mlcomp_tpu.train.export import export_from_checkpoint
        export = export_from_checkpoint(
            str(tmp_path / 'ck_pre' / 'last.msgpack'),
            {'name': 'mlp', 'num_classes': 10, 'hidden': [64],
             'dtype': 'float32'},
            str(tmp_path / 'pre_export'))
        result = run_executor({
            'model': {'name': 'mlp', 'num_classes': 4, 'hidden': [64],
                      'dtype': 'float32', 'params_file': export},
            'dataset': {'name': 'synthetic_images', 'n_train': 128,
                        'n_valid': 64, 'image_size': 8, 'channels': 1,
                        'num_classes': 4},
            'batch_size': 32,
            'stages': [{'name': 's1', 'epochs': 1}],
        }, str(tmp_path / 'ck'))
        assert result['best_score'] is not None

    def test_checkpoint_resume_wins_over_params_file(self, tmp_path):
        """Resume semantics: once a checkpoint exists, params_file is
        ignored (the run continues, it doesn't restart from pretrained)."""
        spec = _digits_spec(epochs=1)
        ck = str(tmp_path / 'ck')
        run_executor(spec, ck)
        # rerun with a params_file that would RAISE if opened
        spec2 = _digits_spec(
            epochs=1, params_file=str(tmp_path / 'does_not_exist.npz'))
        result = run_executor(spec2, ck)
        assert result['samples_per_sec'] == 0  # fully resumed

    def test_wrong_architecture_fails_loud(self, tmp_path):
        bad = str(tmp_path / 'bad.npz')
        np.savez(bad, **{'params/NotALayer/kernel':
                         np.zeros((3, 3), np.float32)})
        with pytest.raises(ValueError, match='ZERO'):
            run_executor(_digits_spec(epochs=1, params_file=bad),
                         str(tmp_path / 'ck'))

    def test_batch_stats_load(self, tmp_path):
        """BatchNorm models round-trip batch_stats through the hook."""
        spec = {
            'model': {'name': 'resnet18', 'num_classes': 4,
                      'dtype': 'float32'},
            'dataset': {'name': 'synthetic_images', 'n_train': 64,
                        'n_valid': 32, 'image_size': 16,
                        'num_classes': 4},
            'batch_size': 16,
            'stages': [{'name': 's1', 'epochs': 1,
                        'optimizer': {'name': 'sgd', 'lr': 0.01}}],
        }
        run_executor(spec, str(tmp_path / 'ck_pre'))
        from mlcomp_tpu.train.export import export_from_checkpoint
        export = export_from_checkpoint(
            str(tmp_path / 'ck_pre' / 'last.msgpack'),
            spec['model'], str(tmp_path / 'rn_export'))
        variables, _ = load_export(export)
        assert 'batch_stats' in variables
        import jax

        from mlcomp_tpu.train.loop import create_train_state
        from mlcomp_tpu.models import create_model
        from mlcomp_tpu.train.optim import make_optimizer
        model = create_model(**spec['model'])
        opt, _ = make_optimizer({'name': 'sgd', 'lr': 0.01}, 10)
        state = create_train_state(
            model, opt, np.zeros((1, 16, 16, 3), np.float32),
            jax.random.PRNGKey(1))
        state2, summary = apply_pretrained(state, export)
        assert len(summary.reinit) == 0 and len(summary.missing) == 0
        got = jax.tree.leaves(state2.batch_stats)
        want = jax.tree.leaves(variables['batch_stats'])
        assert all(np.allclose(g, w) for g, w in zip(got, want))

    def test_sharded_state_load_preserves_shardings(self, tmp_path):
        """Merging into a mesh-placed (boxed/Partitioned) state keeps
        leaf shardings and loads values exactly."""
        import jax

        from mlcomp_tpu.models import create_model
        from mlcomp_tpu.parallel import mesh_from_spec
        from mlcomp_tpu.train.loop import create_train_state
        from mlcomp_tpu.train.optim import make_optimizer
        import flax.linen as nn

        mesh = mesh_from_spec({'dp': -1, 'tp': 2})
        spec = {'name': 'transformer_lm', 'vocab_size': 64,
                'd_model': 32, 'n_layers': 1, 'n_heads': 2,
                'd_ff': 64, 'max_seq_len': 16, 'dtype': 'float32'}
        model = create_model(mesh=mesh, **spec)
        opt, _ = make_optimizer({'name': 'adam', 'lr': 1e-3}, 10)
        sample = np.zeros((2, 16), np.int32)
        state = create_train_state(model, opt, sample,
                                   jax.random.PRNGKey(0), mesh=mesh)
        # export params perturbed so a successful load is observable
        params_host = jax.tree.map(
            lambda x: np.asarray(x) + 0.5,
            nn.meta.unbox(jax.device_get(state.params)))
        export = export_model(str(tmp_path / 'tlm'), params_host, spec)
        state2, summary = apply_pretrained(state, export)
        assert not summary.reinit and not summary.missing
        before = jax.tree.leaves(state.params)
        after = jax.tree.leaves(state2.params)
        for old, new in zip(before, after):
            old_raw = nn.meta.unbox(old)
            new_raw = nn.meta.unbox(new)
            assert new_raw.sharding == old_raw.sharding
            assert np.allclose(np.asarray(new_raw),
                               np.asarray(old_raw) + 0.5)
