"""DB layer tests (parity model: reference db/tests/test_project.py:8-28)."""

import os
import datetime

from mlcomp_tpu.db.enums import TaskStatus
from mlcomp_tpu.db.migration import DEFAULT_LAYOUTS
from mlcomp_tpu.db.models import Dag, Project, Task
from mlcomp_tpu.db.providers import (
    AuxiliaryProvider, ComputerProvider, DagProvider, ProjectProvider,
    QueueProvider, ReportLayoutProvider, TaskProvider,
)
from mlcomp_tpu.utils.misc import now


class TestProject:
    def test_add_and_by_name(self, session):
        provider = ProjectProvider(session)
        provider.add_project('test_proj')
        p = provider.by_name('test_proj')
        assert p is not None and p.name == 'test_proj'
        assert provider.by_name('missing') is None

    def test_get_with_counts(self, session):
        provider = ProjectProvider(session)
        p = provider.add_project('proj2')
        res = provider.get()
        assert res['total'] == 1
        assert res['data'][0]['dag_count'] == 0
        assert p.id is not None


class TestTask:
    def _make_dag(self, session, name='dag1'):
        p = ProjectProvider(session).add_project(name + '_proj')
        dag = Dag(name=name, config='', project=p.id, created=now())
        session.add(dag)
        return dag

    def test_dependency_status(self, session):
        dag = self._make_dag(session)
        tp = TaskProvider(session)
        a = tp.add(Task(name='a', executor='x', dag=dag.id))
        b = tp.add(Task(name='b', executor='x', dag=dag.id))
        tp.add_dependency(b.id, a.id)
        dep = tp.dependency_status([a.id, b.id])
        assert dep[a.id] == set()
        assert dep[b.id] == {int(TaskStatus.NotRan)}
        tp.change_status(a, TaskStatus.Success)
        dep = tp.dependency_status([b.id])
        assert dep[b.id] == {int(TaskStatus.Success)}

    def test_change_status_timestamps(self, session):
        dag = self._make_dag(session, 'dag2')
        tp = TaskProvider(session)
        t = tp.add(Task(name='t', executor='x', dag=dag.id))
        tp.change_status(t, TaskStatus.InProgress)
        t2 = tp.by_id(t.id)
        assert t2.status == int(TaskStatus.InProgress)
        assert isinstance(t2.started, datetime.datetime)
        tp.change_status(t, TaskStatus.Success)
        t3 = tp.by_id(t.id)
        assert t3.finished is not None

    def test_parent_tasks_stats(self, session):
        dag = self._make_dag(session, 'dag3')
        tp = TaskProvider(session)
        parent = tp.add(Task(name='p', executor='x', dag=dag.id,
                             status=int(TaskStatus.Queued)))
        c1 = tp.add(Task(name='c1', executor='x', dag=dag.id,
                         parent=parent.id))
        tp.add(Task(name='c2', executor='x', dag=dag.id, parent=parent.id))
        tp.change_status(c1, TaskStatus.Success)
        stats = tp.parent_tasks_stats()
        assert len(stats) == 1
        p, _, _, counts = stats[0]
        assert p.id == parent.id
        assert counts[int(TaskStatus.Success)] == 1
        assert counts[int(TaskStatus.NotRan)] == 1


class TestDagGraph:
    def test_graph(self, session):
        p = ProjectProvider(session).add_project('gproj')
        dag = Dag(name='g', config='', project=p.id, created=now())
        session.add(dag)
        tp = TaskProvider(session)
        a = tp.add(Task(name='a', executor='xa', dag=dag.id))
        b = tp.add(Task(name='b', executor='xb', dag=dag.id))
        tp.add_dependency(b.id, a.id)
        g = DagProvider(session).graph(dag.id)
        assert len(g['nodes']) == 2
        assert g['edges'] == [
            {'from': a.id, 'to': b.id, 'status': 'NotRan'}]

    def test_get_counts(self, session):
        p = ProjectProvider(session).add_project('gproj2')
        dag = Dag(name='g2', config='', project=p.id, created=now())
        session.add(dag)
        TaskProvider(session).add(
            Task(name='a', executor='xa', dag=dag.id))
        res = DagProvider(session).get({'project': p.id})
        assert res['total'] == 1
        assert res['data'][0]['task_count'] == 1


class TestQueue:
    def test_claim_complete(self, session):
        q = QueueProvider(session)
        m1 = q.enqueue('host_default', {'action': 'execute', 'task_id': 1})
        q.enqueue('host_default', {'action': 'execute', 'task_id': 2})
        claimed = q.claim(['host_default'], 'w1')
        assert claimed is not None
        msg_id, payload = claimed
        assert msg_id == m1 and payload['task_id'] == 1
        q.complete(msg_id)
        assert q.status(msg_id) == 'done'
        # second message still claimable, third claim returns None
        assert q.claim(['host_default'], 'w2') is not None
        assert q.claim(['host_default'], 'w3') is None

    def test_revoke(self, session):
        q = QueueProvider(session)
        m = q.enqueue('qq', {'action': 'execute', 'task_id': 3})
        assert q.revoke(m) is True
        assert q.claim(['qq'], 'w') is None
        assert q.revoke(m) is False  # already revoked


class TestQueueLateFinisher:
    """The lost-update interleaving behind the conditional
    complete()/fail() (db-naked-transition finding on the old
    unconditional ``WHERE id=?``): a worker that stalls past its lease
    still holds the message id; after the supervisor reclaims the
    lease and a second worker claims the message, the first worker's
    late verdict must LOSE, not clobber the live execution. Played
    deterministically — each step is one call, no threads needed."""

    def _reclaimed_and_reclaimed(self, session):
        q = QueueProvider(session)
        m = q.enqueue('lq', {'action': 'execute', 'task_id': 9})
        assert q.claim(['lq'], 'w1')[0] == m     # w1 claims, stalls
        assert q.reclaim(m) is True              # lease expires
        assert q.claim(['lq'], 'w2')[0] == m     # w2 re-claims
        return q, m

    def test_late_complete_loses_to_live_claim(self, session):
        q, m = self._reclaimed_and_reclaimed(session)
        # w1 wakes up and reports success for a claim it no longer owns
        assert q.complete(m, worker='w1') is False
        assert q.status(m) == 'claimed'          # w2 still executing
        # the live claimant's verdict wins
        assert q.complete(m, worker='w2') is True
        assert q.status(m) == 'done'

    def test_late_fail_cannot_seed_duplicate_retry(self, session):
        q, m = self._reclaimed_and_reclaimed(session)
        assert q.fail(m, 'w1 stalled then crashed',
                      worker='w1') is False
        assert q.status(m) == 'claimed'
        assert q.fail(m, 'real failure', worker='w2') is True
        assert q.status(m) == 'failed'

    def test_late_complete_after_reclaim_before_reclaim_loses(
            self, session):
        """The narrower window: reclaimed (pending again) but not yet
        re-claimed. The late complete must not mark a PENDING message
        done — the redelivery would silently vanish."""
        q = QueueProvider(session)
        m = q.enqueue('lq2', {'action': 'execute', 'task_id': 10})
        assert q.claim(['lq2'], 'w1')[0] == m
        assert q.reclaim(m) is True
        assert q.complete(m, worker='w1') is False
        assert q.status(m) == 'pending'          # redelivery survives

    def test_unpinned_complete_still_requires_claimed(self, session):
        """Callers without an identity (tests, tools) still get the
        status guard — only a claimed message can finish."""
        q = QueueProvider(session)
        m = q.enqueue('lq3', {'action': 'execute', 'task_id': 11})
        assert q.complete(m) is False            # pending: refused
        q.claim(['lq3'], 'w1')
        assert q.complete(m) is True
        assert q.complete(m) is False            # already done


class TestQueueReturningFallback:
    """The atomic claim on sqlite < 3.35 (no UPDATE ... RETURNING —
    this class exercises BOTH code paths explicitly so the suite
    covers them regardless of the host's sqlite)."""

    def _flow(self, session):
        q = QueueProvider(session)
        m1 = q.enqueue('hq', {'action': 'execute', 'task_id': 1})
        m2 = q.enqueue('hq', {'action': 'execute', 'task_id': 2})
        first = q.claim(['hq'], 'w1')
        second = q.claim(['hq'], 'w2')
        assert first is not None and second is not None
        # at-most-once: oldest first, never the same message twice
        assert first[0] == m1 and first[1]['task_id'] == 1
        assert second[0] == m2
        assert q.claim(['hq'], 'w3') is None
        assert q.status(m1) == 'claimed'

    def test_fallback_path_claims_at_most_once(self, session,
                                               monkeypatch):
        import mlcomp_tpu.db.providers.queue as qmod
        monkeypatch.setattr(qmod, '_RETURNING_OK', False)
        self._flow(session)

    def test_returning_path_or_live_downgrade(self, session,
                                              monkeypatch):
        """With the flag forced on, claim() either runs the RETURNING
        statement (sqlite >= 3.35) or hits the syntax error ONCE,
        downgrades the module flag and serves the claim through the
        fallback — the caller never sees a difference."""
        import sqlite3

        import mlcomp_tpu.db.providers.queue as qmod
        monkeypatch.setattr(qmod, '_RETURNING_OK', True)
        self._flow(session)
        expected = sqlite3.sqlite_version_info >= (3, 35, 0)
        assert qmod._RETURNING_OK is expected

    def test_fallback_skips_raced_away_candidate(self, session,
                                                 monkeypatch):
        """Two pollers SELECT the same oldest pending candidate; the
        loser's conditional UPDATE claims fewer rows than it selected
        and must move on to the next message instead of returning a
        message someone else owns."""
        import mlcomp_tpu.db.providers.queue as qmod
        monkeypatch.setattr(qmod, '_RETURNING_OK', False)
        q = QueueProvider(session)
        m1 = q.enqueue('rq', {'action': 'execute', 'task_id': 1})
        m2 = q.enqueue('rq', {'action': 'execute', 'task_id': 2})

        real_query = type(session).query
        stolen = {'done': False}

        def steal_between_select_and_update(self_s, sql, params=()):
            rows = real_query(self_s, sql, params)
            if not stolen['done'] and rows \
                    and 'queue_message' in sql and 'pending' in sql \
                    and 'ORDER BY id' in sql:
                stolen['done'] = True
                # another worker wins the candidate mid-flight
                session.execute(
                    "UPDATE queue_message SET status='claimed', "
                    "claimed_by='rival' WHERE id=?", (rows[0]['id'],))
            return rows

        monkeypatch.setattr(type(session), 'query',
                            steal_between_select_and_update)
        claimed = q.claim(['rq'], 'slow-worker')
        monkeypatch.setattr(type(session), 'query', real_query)
        assert claimed is not None
        assert claimed[0] == m2          # m1 was stolen — moved on
        assert q.status(m1) == 'claimed'
        assert q.status(m2) == 'claimed'


class TestMigrationV6:
    def test_v5_db_upgrades_in_place(self, session, tmp_path):
        """A pre-v6 DB (telemetry_span without trace columns, no alert
        table) must upgrade via the guarded ALTERs and accept the new
        insert shape."""
        from mlcomp_tpu.db.core import Session
        from mlcomp_tpu.db.migration import migrate
        from mlcomp_tpu.db.providers.telemetry import (
            TelemetrySpanProvider,
        )
        old = Session(f'sqlite:///{tmp_path}/old.db', key='v5_upgrade')
        try:
            # v5-era schema: the old column set, version pinned to 5
            old.execute(
                'CREATE TABLE telemetry_span ('
                'id INTEGER PRIMARY KEY AUTOINCREMENT, span_id TEXT, '
                'parent_id TEXT, task INTEGER, name TEXT, started REAL, '
                'duration REAL, status TEXT, tags TEXT)')
            old.execute(
                'CREATE TABLE metric ('
                'id INTEGER PRIMARY KEY AUTOINCREMENT, task INTEGER, '
                'name TEXT, kind TEXT, step INTEGER, value REAL, '
                'time TEXT, component TEXT, tags TEXT)')
            old.execute(
                'CREATE TABLE migration_version (version INTEGER)')
            old.execute(
                'INSERT INTO migration_version (version) VALUES (5)')
            migrate(old)
            cols = {r['name'] for r in
                    old.query('PRAGMA table_info(telemetry_span)')}
            assert {'trace_id', 'process_role'} <= cols
            provider = TelemetrySpanProvider(old)
            provider.add_many([('a-1', None, 1, 'x', 0.0, 0.1, 'ok',
                                None, 'tr1', 'worker')])
            (row,) = provider.by_trace('tr1')
            assert row.process_role == 'worker'
            assert old.query('SELECT * FROM alert') == []
        finally:
            Session.cleanup('v5_upgrade')


class TestLayouts:
    def test_seeded(self, session):
        lp = ReportLayoutProvider(session)
        layouts = lp.all_layouts()
        for name in DEFAULT_LAYOUTS:
            assert name in layouts

    def test_extend_resolution(self, session):
        lp = ReportLayoutProvider(session)
        resolved = lp.resolved('img_classify')
        # img_classify extends classify extends base
        assert 'throughput' in resolved['items']
        assert 'accuracy' in resolved['items']
        assert 'img_classify' in resolved['items']
        assert resolved['metric']['name'] == 'accuracy'


class TestComputerAux:
    def test_computer_roundtrip(self, session):
        from mlcomp_tpu.db.models import Computer
        cp = ComputerProvider(session)
        cp.create_or_update(
            Computer(name='host1', cores=8, cpu=16, memory=32), 'name')
        cp.current_usage('host1', {'cpu': 10})
        c = cp.by_name('host1')
        assert c.cores == 8
        assert 'cpu' in c.usage

    def test_auxiliary(self, session):
        ap = AuxiliaryProvider(session)
        ap.create_or_update('supervisor', {'tick': 1})
        ap.create_or_update('supervisor', {'tick': 2})
        assert ap.get()['supervisor']['tick'] == 2


class TestQueueConcurrency:
    def test_multiprocess_claims_exactly_once(self, session):
        """N OS processes hammering claim() on one queue: every message
        claimed exactly once (WAL sqlite + immediate-claim UPDATE is the
        broker's core safety property — threads can't prove it, the GIL
        serializes them)."""
        import json
        import subprocess
        import sys

        import mlcomp_tpu
        from mlcomp_tpu.db.providers import QueueProvider

        qp = QueueProvider(session)
        n_msgs, n_workers = 40, 4
        for i in range(n_msgs):
            qp.enqueue('conc_q', {'i': i})

        script = r'''
import json, os, sys
from mlcomp_tpu.db.core import Session
from mlcomp_tpu.db.providers import QueueProvider
qp = QueueProvider(Session.create_session(key=f'w{os.getpid()}'))
claimed = []
misses = 0
while misses < 5:
    msg = qp.claim(['conc_q'], worker=f'w{os.getpid()}')
    if msg is None:
        misses += 1
        continue
    msg_id, _payload = msg
    claimed.append(msg_id)
    qp.complete(msg_id)
print(json.dumps(claimed))
'''
        env = dict(os.environ,
                   MLCOMP_TPU_ROOT=mlcomp_tpu.ROOT_FOLDER,
                   JAX_PLATFORMS='cpu')
        procs = [subprocess.Popen(
            [sys.executable, '-c', script], stdout=subprocess.PIPE,
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            for _ in range(n_workers)]
        all_claimed = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            assert p.returncode == 0
            all_claimed.extend(json.loads(out.strip().splitlines()[-1]))
        assert len(all_claimed) == n_msgs, (
            f'{len(all_claimed)} claims for {n_msgs} messages')
        assert len(set(all_claimed)) == n_msgs, 'double-claim detected'
