"""Telemetry subsystem tests: span nesting/flush, metric-series
persistence round trips, API endpoints, profiler control plane, and the
hot-path overhead guard."""

import json
import time
import urllib.request

import numpy as np
import pytest

from mlcomp_tpu import TOKEN
from mlcomp_tpu.db.models import Dag, Task
from mlcomp_tpu.db.providers import (
    DagProvider, MetricProvider, TaskProvider, TelemetrySpanProvider,
)
from mlcomp_tpu.telemetry import (
    Histogram, MetricRecorder, SpanBuffer, TaskProfiler, flush_spans,
    request_stop, request_trace, span, trace_status,
)
from mlcomp_tpu.utils.misc import now


def make_task(session, name='t'):
    from mlcomp_tpu.db.providers import ProjectProvider
    provider = ProjectProvider(session)
    project = provider.by_name('p_telemetry')
    if project is None:
        provider.add_project('p_telemetry')
        project = provider.by_name('p_telemetry')
    dag = Dag(name='d', project=project.id, config='', created=now(),
              docker_img='default')
    DagProvider(session).add(dag)
    task = Task(name=name, executor='e', dag=dag.id, status=0)
    TaskProvider(session).add(task)
    return task


class TestSpans:
    def test_nesting_and_flush(self, session):
        task = make_task(session)
        buf = SpanBuffer()
        with span('outer', task=task.id, buffer=buf) as outer:
            outer.tag('k', 'v')
            with span('inner', buffer=buf):
                time.sleep(0.01)
        assert flush_spans(session, buf) == 2
        provider = TelemetrySpanProvider(session)
        rows = provider.by_task(task.id)
        by_name = {r.name: r for r in rows}
        # inner inherits the task AND parents to outer automatically
        assert by_name['inner'].parent_id == by_name['outer'].span_id
        assert by_name['inner'].task == task.id
        assert by_name['inner'].duration >= 0.01
        assert by_name['outer'].duration >= by_name['inner'].duration
        tree = provider.tree(task.id)
        assert len(tree) == 1
        assert tree[0]['tags'] == {'k': 'v'}
        assert [c['name'] for c in tree[0]['children']] == ['inner']

    def test_error_status_recorded(self, session):
        task = make_task(session)
        buf = SpanBuffer()
        with pytest.raises(ValueError):
            with span('boom', task=task.id, buffer=buf):
                raise ValueError('x')
        flush_spans(session, buf)
        (row,) = TelemetrySpanProvider(session).by_task(task.id)
        assert row.status == 'error'

    def test_ring_bounds_and_drop_count(self):
        buf = SpanBuffer(capacity=4)
        for i in range(7):
            with span(f's{i}', buffer=buf):
                pass
        assert len(buf) == 4
        assert buf.dropped_count == 3
        names = [r['name'] for r in buf.drain()]
        assert names == ['s3', 's4', 's5', 's6']  # oldest dropped

    def test_flush_empty_and_sessionless(self, session):
        buf = SpanBuffer()
        assert flush_spans(session, buf) == 0
        with span('s', buffer=buf):
            pass
        assert flush_spans(None, buf) == 0


class TestMetrics:
    def test_series_round_trip_across_flush_boundary(self, session):
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             component='train', flush_every=5)
        for i in range(12):     # crosses two auto-flush boundaries
            rec.series('loss', np.float32(1.0 - 0.05 * i), step=i)
        rec.flush()
        series = MetricProvider(session).series(task_id=task.id)
        points = series['loss']
        assert [p['step'] for p in points] == list(range(12))
        assert points[0]['value'] == pytest.approx(1.0)
        assert points[-1]['value'] == pytest.approx(0.45)

    def test_device_array_values_convert_at_flush(self, session):
        import jax.numpy as jnp
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             flush_every=10 ** 9)
        rec.series('loss', jnp.float32(0.25), step=0)
        rec.flush()
        series = MetricProvider(session).series(task_id=task.id)
        assert series['loss'][0]['value'] == pytest.approx(0.25)

    def test_counters_and_histograms_emit_summaries(self, session):
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             flush_every=10 ** 9)
        rec.count('dispatched', 3)
        rec.count('dispatched', 2)
        for v in (1.0, 2.0, 3.0, 4.0):
            rec.observe('lat_ms', v)
        rec.flush()
        series = MetricProvider(session).series(task_id=task.id)
        assert series['dispatched'][0]['value'] == 5.0
        assert series['dispatched'][0]['kind'] == 'counter'
        assert series['lat_ms.count'][0]['value'] == 4.0
        assert series['lat_ms.min'][0]['value'] == 1.0
        assert series['lat_ms.max'][0]['value'] == 4.0
        assert series['lat_ms.p50'][0]['value'] == pytest.approx(2.5)

    def test_sessionless_recorder_drops_and_counts(self):
        rec = MetricRecorder(flush_every=10 ** 9)
        rec.series('x', 1.0, step=0)
        assert rec.flush() == 0
        assert rec.dropped_count == 1

    def test_histogram_summary(self):
        h = Histogram()
        assert h.summary() == {}
        for v in range(100):
            h.observe(float(v))
        s = h.summary()
        assert s['count'] == 100
        assert s['mean'] == pytest.approx(49.5)
        assert s['p99'] >= 95

    def test_series_array_bulk(self, session):
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             flush_every=10 ** 9)
        rec.series_array('loss', np.linspace(1, 0, 5), start_step=10)
        rec.flush()
        points = MetricProvider(session).series(task_id=task.id)['loss']
        assert [p['step'] for p in points] == [10, 11, 12, 13, 14]


@pytest.fixture()
def api(session):
    from mlcomp_tpu.server.api import ApiServer
    server = ApiServer(host='127.0.0.1', port=0).start_background()
    base = f'http://127.0.0.1:{server.port}'

    def call(path, data=None, token=TOKEN, method='POST'):
        if method == 'GET':
            req = urllib.request.Request(base + path)
        else:
            req = urllib.request.Request(
                base + path, data=json.dumps(data or {}).encode(),
                headers={'Content-Type': 'application/json'})
        if token is not None:
            req.add_header('Authorization', token)
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    call.base = base    # raw-fetch routes (text /metrics) need the url
    yield call
    server.shutdown()


class TestApi:
    def _seed(self, session):
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             component='train', flush_every=10 ** 9)
        for i in range(4):
            rec.series('loss', 1.0 - 0.1 * i, step=i)
            rec.series('throughput', 100.0 + i, step=i)
        rec.flush()
        buf = SpanBuffer()
        with span('task.pipeline', task=task.id, buffer=buf):
            with span('task.execute', buffer=buf):
                pass
        flush_spans(session, buf)
        return task

    def test_get_series(self, api, session):
        task = self._seed(session)
        out = api(f'/telemetry/series?task={task.id}', method='GET',
                  token=None)  # no-auth introspection tier
        assert out['task'] == task.id
        assert [p['value'] for p in out['series']['loss']] == \
            pytest.approx([1.0, 0.9, 0.8, 0.7])
        assert len(out['series']['throughput']) == 4
        named = api(f'/telemetry/series?task={task.id}&name=loss',
                    method='GET', token=None)
        assert list(named['series']) == ['loss']

    def test_get_spans(self, api, session):
        task = self._seed(session)
        out = api(f'/telemetry/spans?task={task.id}', method='GET',
                  token=None)
        assert len(out['spans']) == 1
        root = out['spans'][0]
        assert root['name'] == 'task.pipeline'
        assert [c['name'] for c in root['children']] == ['task.execute']

    def test_post_routes(self, api, session):
        task = self._seed(session)
        out = api('/api/telemetry/series', {'task': task.id})
        assert 'loss' in out['series']
        out = api('/api/telemetry/spans', {'task': task.id})
        assert out['spans'][0]['name'] == 'task.pipeline'

    def test_spans_requires_task(self, api):
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            api('/api/telemetry/spans', {})
        assert e.value.code == 400

    def test_non_integer_task_is_client_error(self, api):
        # GET args arrive as strings; garbage is the caller's 400,
        # not a 500 out of int()
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            api('/telemetry/series?task=nope', method='GET', token=None)
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            api('/api/telemetry/spans', {'task': 'nope'})
        assert e.value.code == 400

    def test_profile_toggle_requires_auth(self, api, session):
        import urllib.error
        task = self._seed(session)
        with pytest.raises(urllib.error.HTTPError) as e:
            api('/api/telemetry/profile',
                {'task': task.id, 'action': 'start'}, token='wrong')
        assert e.value.code == 401
        out = api('/api/telemetry/profile',
                  {'task': task.id, 'action': 'start'})
        assert out['status'] == 'requested'
        out = api('/api/telemetry/profile',
                  {'task': task.id, 'action': 'status'})
        assert out['status'] == 'requested'


class TestProfilerControl:
    def test_request_trace_drives_worker_state_machine(self, session,
                                                       tmp_path):
        task = make_task(session)
        started, stopped = [], []
        prof = TaskProfiler(session, task.id, str(tmp_path),
                            tracer_start=started.append,
                            tracer_stop=lambda: stopped.append(True))
        assert prof.poll() is False            # nothing requested
        request_trace(session, task.id, max_epochs=2)
        assert prof.poll() is True             # starts the trace
        assert len(started) == 1
        assert trace_status(session, task.id)['status'] == 'tracing'
        assert prof.poll() is True             # epoch 1 of 2
        assert prof.poll() is False            # epoch 2 → auto stop
        assert stopped == [True]
        status = trace_status(session, task.id)
        assert status['status'] == 'done'
        assert status['epochs'] == 2

    def test_stop_request_wins_over_max_epochs(self, session, tmp_path):
        task = make_task(session)
        prof = TaskProfiler(session, task.id, str(tmp_path),
                            tracer_start=lambda d: None,
                            tracer_stop=lambda: None)
        request_trace(session, task.id, max_epochs=100)
        assert prof.poll() is True
        request_stop(session, task.id)
        assert prof.poll() is False
        assert trace_status(session, task.id)['status'] == 'done'

    def test_close_stops_open_trace(self, session, tmp_path):
        task = make_task(session)
        stopped = []
        prof = TaskProfiler(session, task.id, str(tmp_path),
                            tracer_start=lambda d: None,
                            tracer_stop=lambda: stopped.append(True))
        request_trace(session, task.id, max_epochs=100)
        prof.poll()
        prof.close()
        assert stopped == [True]
        assert trace_status(session, task.id)['status'] == 'done'


class TestTrainLoopWiring:
    def test_jax_train_records_per_step_series(self, session, tmp_path):
        """The acceptance-criterion path: a jax_train run records
        per-step loss + throughput from INSIDE the loop, queryable via
        the metric provider by task id."""
        from mlcomp_tpu.train import JaxTrain

        class DummyStep:
            def start(self, *a, **k):
                pass

            def info(self, m):
                pass

            def debug(self, m):
                pass

            def error(self, m):
                pass

            def end_all(self):
                pass

        task = make_task(session)
        ex = JaxTrain(
            model={'name': 'mlp', 'hidden': [16], 'num_classes': 4},
            dataset={'name': 'synthetic_images', 'n_train': 256,
                     'n_valid': 64, 'image_size': 8, 'channels': 1,
                     'num_classes': 4},
            loss='softmax_ce', batch_size=32, epochs=2,
            telemetry={'flush_every': 16},
            checkpoint_dir=str(tmp_path / 'ck'))
        ex.step = DummyStep()
        ex.task = task
        ex.dag = DagProvider(session).by_id(task.dag)
        ex.session = session
        ex.additional_info = {}
        ex.work()

        series = MetricProvider(session).series(task_id=task.id)
        assert 'loss' in series and 'throughput' in series
        # 2 epochs x 8 steps — every step's loss recorded in order
        assert [p['step'] for p in series['loss']] == list(range(16))
        assert 'epoch_time_s' in series
        assert 'epoch_throughput' in series

    def test_telemetry_false_disables_recording(self, session,
                                                tmp_path):
        from mlcomp_tpu.train import JaxTrain

        class DummyStep:
            def start(self, *a, **k):
                pass

            def info(self, m):
                pass

            def debug(self, m):
                pass

            def error(self, m):
                pass

            def end_all(self):
                pass

        task = make_task(session)
        ex = JaxTrain(
            model={'name': 'mlp', 'hidden': [8], 'num_classes': 4},
            dataset={'name': 'synthetic_images', 'n_train': 64,
                     'n_valid': 32, 'image_size': 8, 'channels': 1,
                     'num_classes': 4},
            loss='softmax_ce', batch_size=32, epochs=1,
            telemetry=False, checkpoint_dir=str(tmp_path / 'ck'))
        ex.step = DummyStep()
        ex.task = task
        ex.dag = DagProvider(session).by_id(task.dag)
        ex.session = session
        ex.additional_info = {}
        ex.work()
        assert MetricProvider(session).series(task_id=task.id) == {}


class TestOverheadGuard:
    def test_instrumented_step_within_5pct_of_bare(self):
        """The telemetry hot path (perf_counter + 3 buffered appends)
        must be noise against a real step: instrumented = bare +
        wrapper cost, so the guard asserts the wrapper's isolated
        per-step cost is under 5% of the measured bare step time.
        (Differencing two timed loops cannot resolve a few-percent
        budget through this harness's ±10% scheduler drift — the same
        reason bench.py publishes ``telemetry_overhead_pct`` from the
        isolated measurement.)"""
        import jax
        import jax.numpy as jnp

        from mlcomp_tpu.train.loop import instrumented_step

        @jax.jit
        def step(state, x, y):
            return state, {'loss': jnp.sum(jnp.dot(x, x))}

        x = jnp.ones((512, 512), jnp.float32)
        step(0.0, x, None)          # compile
        bare = float('inf')
        for _ in range(7):
            t0 = time.perf_counter()
            for _ in range(50):
                state, metrics = step(0.0, x, None)
            jax.block_until_ready(metrics['loss'])
            bare = min(bare, (time.perf_counter() - t0) / 50)

        # wrapper cost in isolation: the identical wrapper around a
        # no-op step, so the loop measures ONLY the telemetry path
        rec = MetricRecorder(flush_every=10 ** 9, capacity=10 ** 6)
        fake_metrics = {'loss': np.float32(0.5)}
        instr = instrumented_step(
            lambda s, xb, yb: (s, fake_metrics), rec, batch_size=512)
        n = 20000
        t0 = time.perf_counter()
        for _ in range(n):
            instr(0.0, None, None)
        wrapper_cost = (time.perf_counter() - t0) / n

        assert wrapper_cost <= bare * 0.05, (wrapper_cost, bare)


class TestDeviceStats:
    def test_record_device_stats_noop_on_cpu(self, session):
        from mlcomp_tpu.telemetry import (
            device_memory_stats, record_device_stats,
        )
        stats = device_memory_stats()
        # jax IS imported in the test process: every local device is
        # reported (CPU devices usually carry no bytes_limit)
        assert isinstance(stats, list)
        rec = MetricRecorder(session=session, task=None,
                             flush_every=10 ** 9)
        record_device_stats(rec)    # must not raise without HBM stats

    def test_mfu_arithmetic(self):
        from mlcomp_tpu.telemetry import mfu
        # 1 TFLOP/step at 100 steps/s on 1 chip of 200 TFLOPs → 0.5
        assert mfu(1e12, 100, 1, 200) == pytest.approx(0.5)

    def test_compiled_cost_on_cpu_step(self):
        import jax
        import jax.numpy as jnp

        from mlcomp_tpu.telemetry import compiled_cost

        @jax.jit
        def f(x):
            return jnp.dot(x, x)

        cost = compiled_cost(f, jnp.ones((64, 64), jnp.float32))
        # XLA:CPU reports flops for a matmul; {} acceptable only if the
        # backend hides cost analysis — either way the call must not
        # raise
        if cost:
            assert cost['flops'] is None or cost['flops'] > 0


class TestServingDriverHistogram:
    def test_chain_runner_observes_latency_after_warm(self):
        """ops/serving_stack.make_chain_runner with a recorder: each
        call after the compile+warm first one lands a per-stack latency
        sample in the named histogram."""
        import jax.numpy as jnp

        from mlcomp_tpu.ops.serving_stack import make_chain_runner

        rec = MetricRecorder(flush_every=10 ** 9)
        run = make_chain_runner(
            lambda x: x * 1.0, [], jnp.ones((4, 4), jnp.float32),
            reps=3, recorder=rec, metric='serving.toy_ms')
        run()                       # compile+warm: NOT recorded
        assert rec.histogram_summaries() == {}
        run()
        run()
        summary = rec.histogram_summaries()['serving.toy_ms']
        assert summary['count'] == 2
        assert summary['min'] >= 0


class TestSupervisorTelemetry:
    def test_tick_records_gauges(self, session):
        from mlcomp_tpu.server.supervisor import SupervisorBuilder
        sup = SupervisorBuilder(session=session)
        sup.build()
        sup.telemetry.flush()
        series = MetricProvider(session).series(component='supervisor')
        assert 'supervisor.tick_ms' in series
        assert series['supervisor.tick_ms'][0]['value'] >= 0


class TestApiLimits:
    """GET/POST /telemetry/series|spans: limit/offset are validated
    (negative/garbage -> 400) and capped, never handed raw to SQL."""

    def _seed(self, session):
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             component='train', flush_every=10 ** 9)
        for i in range(6):
            rec.series('loss', 1.0 - 0.1 * i, step=i)
        rec.flush()
        buf = SpanBuffer()
        with span('task.pipeline', task=task.id, buffer=buf):
            with span('task.execute', buffer=buf):
                pass
        flush_spans(session, buf)
        return task

    def test_negative_limit_is_400(self, api, session):
        import urllib.error
        task = self._seed(session)
        for url in (f'/telemetry/series?task={task.id}&limit=-1',
                    f'/telemetry/spans?task={task.id}&offset=-5'):
            with pytest.raises(urllib.error.HTTPError) as e:
                api(url, method='GET', token=None)
            assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            api('/api/telemetry/series',
                {'task': task.id, 'limit': 'lots'})
        assert e.value.code == 400

    def test_limit_and_offset_page_series(self, api, session):
        task = self._seed(session)
        out = api(f'/telemetry/series?task={task.id}&limit=2',
                  method='GET', token=None)
        assert sum(len(v) for v in out['series'].values()) == 2
        page2 = api(
            f'/telemetry/series?task={task.id}&limit=2&offset=2',
            method='GET', token=None)
        steps = [p['step'] for p in page2['series']['loss']]
        assert steps == [2, 3]

    def test_spans_limit(self, api, session):
        task = self._seed(session)
        out = api(f'/telemetry/spans?task={task.id}&limit=1',
                  method='GET', token=None)
        assert len(out['spans']) == 1
        assert out['spans'][0]['children'] == []

    def test_huge_limit_is_capped_not_error(self, api, session):
        task = self._seed(session)
        out = api(f'/telemetry/series?task={task.id}&limit=999999999',
                  method='GET', token=None)
        assert len(out['series']['loss']) == 6

    def test_tail_returns_newest_window_per_name(self, api, session):
        """tail=N: the newest N samples of EVERY name, each ascending
        — the dashboard performance card's read (a plain ascending
        limit truncates the newest samples of later-sorting names)."""
        task = self._seed(session)
        out = api(f'/telemetry/series?task={task.id}&tail=2',
                  method='GET', token=None)
        steps = [p['step'] for p in out['series']['loss']]
        assert steps == [4, 5]          # newest two, ascending
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            api('/telemetry/series?tail=2', method='GET', token=None)
        assert e.value.code == 400      # tail requires task
        with pytest.raises(urllib.error.HTTPError) as e:
            api(f'/telemetry/series?task={task.id}&tail=0',
                method='GET', token=None)
        assert e.value.code == 400


class TestTraceContext:
    def test_span_records_trace_and_role(self, session):
        from mlcomp_tpu.telemetry import new_trace_id
        task = make_task(session)
        tid = new_trace_id()
        buf = SpanBuffer()
        with span('outer', task=task.id, buffer=buf, trace_id=tid,
                  role='supervisor'):
            # nested spans do NOT auto-inherit the explicit arg — they
            # read the process context, unset here
            with span('inner', buffer=buf, trace_id=tid, role='worker'):
                pass
        flush_spans(session, buf)
        from mlcomp_tpu.db.providers import TelemetrySpanProvider
        rows = {r.name: r for r in
                TelemetrySpanProvider(session).by_task(task.id)}
        assert rows['outer'].trace_id == tid
        assert rows['outer'].process_role == 'supervisor'
        assert rows['inner'].trace_id == tid
        assert rows['inner'].process_role == 'worker'

    def test_context_env_round_trip(self):
        from mlcomp_tpu.telemetry import trace_context_env
        env = trace_context_env(trace_id='abc123',
                                process_role='train')
        assert env == {'MLCOMP_TRACE_ID': 'abc123',
                       'MLCOMP_PROCESS_ROLE': 'train'}

    def test_trace_tree_assembles_across_processes(self, api, session):
        """Acceptance: one trace_id joins spans from 3 DISTINCT
        processes — supervisor (this process), worker and train (real
        subprocess entries that pick the context up from the
        environment) — and GET /telemetry/trace/<id> returns the
        assembled tree."""
        import os
        import subprocess
        import sys
        from mlcomp_tpu.db.providers import TelemetrySpanProvider
        from mlcomp_tpu.telemetry import new_trace_id, trace_context_env

        task = make_task(session)
        tid = new_trace_id()
        buf = SpanBuffer()
        with span('supervisor.dispatch', task=task.id, buffer=buf,
                  trace_id=tid, role='supervisor'):
            pass
        flush_spans(session, buf)

        child_src = (
            'import sys\n'
            'from mlcomp_tpu.db.core import Session\n'
            'from mlcomp_tpu.telemetry import span, flush_spans\n'
            's = Session.create_session()\n'
            'with span(sys.argv[1], task=int(sys.argv[2])):\n'
            '    pass\n'
            'raise SystemExit(0 if flush_spans(s) == 1 else 1)\n')
        for name, role in (('task.pipeline', 'worker'),
                           ('train.work', 'train')):
            env = {**os.environ,
                   'MLCOMP_TPU_KEEP_ROOT': '1',  # don't wipe the
                   # parent's sandbox on child import
                   **trace_context_env(trace_id=tid,
                                       process_role=role)}
            subprocess.run(
                [sys.executable, '-c', child_src, name, str(task.id)],
                env=env, check=True, timeout=120)

        tree = TelemetrySpanProvider(session).trace_tree(tid)
        assert tree['span_count'] == 3
        assert {p['role'] for p in tree['processes']} == \
            {'supervisor', 'worker', 'train'}
        # three DISTINCT pids — the span-id prefix is the pid
        assert len({p['pid'] for p in tree['processes']}) == 3

        out = api(f'/telemetry/trace/{tid}', method='GET', token=None)
        assert out['span_count'] == 3
        assert {s['name'] for s in out['spans']} == \
            {'supervisor.dispatch', 'task.pipeline', 'train.work'}
        for s in out['spans']:
            assert s['trace_id'] == tid

    def test_trace_api_requires_id(self, api):
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            api('/api/telemetry/trace', {})
        assert e.value.code == 400

    def test_unknown_trace_is_empty_not_error(self, api):
        out = api('/telemetry/trace/nope', method='GET', token=None)
        assert out['span_count'] == 0
        assert out['spans'] == []


class TestCrashFlush:
    def test_sigterm_flushes_spans_and_metrics(self, session):
        """The satellite: a SIGTERM'd task process must not take its
        telemetry down with it — the handler converts the signal into
        SystemExit (so the open span exits with status=error) and the
        atexit drain lands both buffers in the DB."""
        import os
        import subprocess
        import sys
        from mlcomp_tpu.db.providers import TelemetrySpanProvider

        task = make_task(session)
        child_src = (
            'import os, signal, sys, time\n'
            'from mlcomp_tpu.db.core import Session\n'
            'from mlcomp_tpu.telemetry import MetricRecorder, span\n'
            'from mlcomp_tpu.worker.tasks import _install_crash_flush\n'
            's = Session.create_session()\n'
            'task = int(sys.argv[1])\n'
            'rec = MetricRecorder(session=s, task=task,\n'
            '                     component="train",\n'
            '                     flush_every=10 ** 9)\n'
            'rec.series("loss", 0.5, step=0)\n'
            '_install_crash_flush(s)\n'
            'with span("doomed", task=task):\n'
            '    os.kill(os.getpid(), signal.SIGTERM)\n'
            '    time.sleep(60)\n')
        proc = subprocess.run(
            [sys.executable, '-c', child_src, str(task.id)],
            env={**os.environ, 'MLCOMP_TPU_KEEP_ROOT': '1'},
            timeout=120)
        assert proc.returncode == 143        # SystemExit(143), not -15

        (row,) = TelemetrySpanProvider(session).by_task(task.id)
        assert row.name == 'doomed'
        assert row.status == 'error'         # SIGTERM mid-span
        series = MetricProvider(session).series(task_id=task.id)
        assert series['loss'][0]['value'] == pytest.approx(0.5)

class TestStepAttribution:
    def test_phase_split_and_series_emission(self, session):
        from mlcomp_tpu.telemetry import StepAttribution
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             component='train', flush_every=10 ** 9)
        attr = StepAttribution(recorder=rec)
        for step in range(3):
            attr.begin('data_wait')
            time.sleep(0.002)
            attr.begin('h2d')
            attr.begin('compute')
            time.sleep(0.005)
            attr.begin('telemetry')
            attr.step_end(step=step)
        assert attr.steps == 3
        totals = attr.totals_ms()
        assert totals['compute'] > totals['data_wait'] > 0
        eff = attr.efficiency()
        assert 0.0 < eff < 1.0
        assert eff > 0.5            # compute slept longer
        rec.flush()
        series = MetricProvider(session).series(task_id=task.id)
        for phase in ('data_wait', 'h2d', 'compute', 'telemetry'):
            pts = series[f'step.phase.{phase}_ms']
            assert [p['step'] for p in pts] == [0, 1, 2]

    def test_emit_epoch_gauges_efficiency_and_resets(self, session):
        from mlcomp_tpu.telemetry import StepAttribution
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             component='train', flush_every=10 ** 9)
        attr = StepAttribution(recorder=rec)
        attr.begin('compute')
        time.sleep(0.002)
        attr.step_end(step=0)
        out = attr.emit_epoch(epoch=0)
        assert out['efficiency'] == pytest.approx(1.0)
        assert out['steps'] == 1
        assert attr.steps == 0 and attr.totals_ms() == {}
        rec.flush()
        series = MetricProvider(session).series(task_id=task.id)
        (pt,) = series['step.pipeline_efficiency']
        assert pt['value'] == pytest.approx(1.0)
        assert pt['step'] == 0

    def test_no_steps_means_no_verdict(self):
        from mlcomp_tpu.telemetry import StepAttribution
        attr = StepAttribution()
        assert attr.efficiency() is None
        assert attr.emit_epoch()['efficiency'] is None

    def test_instrumented_step_emits_phases(self, session):
        """The production wiring: instrumented_step marks compute/
        telemetry and closes each step — step.phase.* series appear
        without the executor doing anything per-step."""
        from mlcomp_tpu.telemetry import StepAttribution
        from mlcomp_tpu.train.loop import instrumented_step
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             component='train', flush_every=10 ** 9)
        attr = StepAttribution(recorder=rec)
        instr = instrumented_step(
            lambda s, x, y: (s, {'loss': np.float32(0.1)}), rec,
            batch_size=8, attribution=attr)
        for _ in range(4):
            attr.begin('data_wait')
            instr(None, None, None)
        rec.flush()
        series = MetricProvider(session).series(task_id=task.id)
        assert len(series['step.phase.compute_ms']) == 4
        assert len(series['step.phase.data_wait_ms']) == 4
        assert 'step.phase.telemetry_ms' in series

    def test_prefetch_batches_marks_input_phases(self):
        from mlcomp_tpu.parallel import mesh_from_spec
        from mlcomp_tpu.telemetry import StepAttribution
        from mlcomp_tpu.train.data import (
            iterate_batches, prefetch_batches,
        )
        mesh = mesh_from_spec({'dp': -1})
        attr = StepAttribution()
        x = np.random.RandomState(0).rand(32, 8, 8, 1).astype(
            np.float32)
        y = np.zeros(32, np.int32)
        n = 0
        for bx, by in prefetch_batches(
                iterate_batches(x, y, 8), mesh, attribution=attr):
            attr.begin('compute')
            n += 1
        attr.step_end()
        assert n == 4
        totals = attr.totals_ms()
        assert totals.get('data_wait', 0) > 0
        assert totals.get('h2d', 0) > 0


class TestCompileEvents:
    def test_shape_varying_jit_records_compiles_with_steps(
            self, session):
        """Shape-varying jit calls after install land as
        compile.backend_ms samples carrying the stamped step."""
        import jax
        import jax.numpy as jnp

        from mlcomp_tpu.telemetry import CompileEventRecorder
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             component='train', flush_every=10 ** 9)
        comp = CompileEventRecorder(recorder=rec)
        if not comp.install():
            pytest.skip('jax.monitoring hooks unavailable')
        try:
            @jax.jit
            def f(x):
                return x * 2 + 1

            for i, n in enumerate((3, 5, 7)):
                comp.step = 100 + i
                f(jnp.ones((n,)))       # new shape → recompile
        finally:
            comp.uninstall()
        assert len(comp.events) >= 3
        rec.flush()
        series = MetricProvider(session).series(task_id=task.id)
        pts = series['compile.backend_ms']
        assert len(pts) >= 3
        steps = {p['step'] for p in pts}
        assert {100, 101, 102} <= steps
        assert all(p['value'] > 0 for p in pts)

    def test_uninstall_stops_recording(self):
        import jax
        import jax.numpy as jnp

        from mlcomp_tpu.telemetry import CompileEventRecorder
        comp = CompileEventRecorder()
        if not comp.install():
            pytest.skip('jax.monitoring hooks unavailable')
        comp.uninstall()

        @jax.jit
        def g(x):
            return x + 3

        g(jnp.ones((11,)))
        assert len(comp.events) == 0

    def test_reinstall_after_uninstall_records_again(self):
        import jax
        import jax.numpy as jnp

        from mlcomp_tpu.telemetry import CompileEventRecorder
        comp = CompileEventRecorder()
        if not comp.install():
            pytest.skip('jax.monitoring hooks unavailable')
        comp.uninstall()
        assert comp.install() is True    # re-arm resets the dead flag

        @jax.jit
        def h(x):
            return x - 7

        try:
            h(jnp.ones((13,)))
            assert len(comp.events) >= 1
        finally:
            comp.uninstall()

    def test_install_without_jax_monitoring_is_noop(self, monkeypatch):
        import sys as _sys

        from mlcomp_tpu.telemetry import CompileEventRecorder
        monkeypatch.setitem(_sys.modules, 'jax.monitoring', None)
        comp = CompileEventRecorder()
        assert comp.install() is False
        assert comp.installed is False

    def test_tripwire_flags_outlier_not_baseline(self, session):
        from mlcomp_tpu.telemetry import HostSyncTripwire
        task = make_task(session)
        rec = MetricRecorder(session=session, task=task.id,
                             component='train', flush_every=10 ** 9)
        wire = HostSyncTripwire(recorder=rec, factor=10.0, min_ms=50.0,
                                warmup_steps=5)
        for step in range(8):
            assert wire.observe(10.0, step=step) is False
        assert wire.observe(900.0, step=8) is True     # 90x median
        assert wire.observe(10.0, step=9) is False     # baseline clean
        assert wire.suspects == 1
        rec.flush()
        series = MetricProvider(session).series(task_id=task.id)
        (pt,) = series['host_sync.suspect_ms']
        assert pt['step'] == 8 and pt['value'] == pytest.approx(900.0)

    def test_tripwire_quiet_during_warmup(self):
        from mlcomp_tpu.telemetry import HostSyncTripwire
        wire = HostSyncTripwire(warmup_steps=10)
        # huge first interval (the compile step) must not flag: the
        # baseline is not established yet
        assert wire.observe(5000.0) is False

    def test_instrumented_step_exempts_compile_steps(self):
        """A step whose interval contains a recorded compile is slow
        for a KNOWN reason — the tripwire must not double-report it."""
        from mlcomp_tpu.telemetry import (
            CompileEventRecorder, HostSyncTripwire,
        )
        from mlcomp_tpu.train.loop import instrumented_step
        rec = MetricRecorder(flush_every=10 ** 9)
        comp = CompileEventRecorder()
        flagged = []

        class Wire(HostSyncTripwire):
            def observe(self, dt_ms, step=None):
                flagged.append(step)
                return False

        instr = instrumented_step(
            lambda s, x: (s, {}), rec, attribution=None,
            tripwire=Wire(), compile_events=comp)
        instr(None, None)               # first step: no interval
        comp._dirty = True              # a compile landed mid-step
        instr(None, None)               # exempt
        instr(None, None)               # observed again
        assert flagged == [2]


class TestTraceCorrelatedLogs:
    def test_formatter_injects_trace_context(self):
        import logging

        from mlcomp_tpu.telemetry import set_trace_context
        from mlcomp_tpu.utils.logging import create_logger
        logger = create_logger(name='mlcomp_tpu_tracetest')
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(self.format(record))

        cap = Capture()
        cap.setFormatter(logging.Formatter('%(trace)s %(message)s'))
        logger.addHandler(cap)
        try:
            set_trace_context('feedbeef12345678', 'train')
            logger.info('inside the dispatch')
            set_trace_context(None)
            logger.info('outside any trace')
        finally:
            set_trace_context(None)
            logger.removeHandler(cap)
        assert '[trace=feedbeef12345678 role=train]' in records[0]
        assert 'trace=' not in records[1]

    def test_grep_by_trace_id_finds_the_line(self):
        """The satellite's contract: one trace id greps out the log
        lines of that dispatch from the standard formatter."""
        import logging

        from mlcomp_tpu.telemetry import new_trace_id, set_trace_context
        from mlcomp_tpu.utils.logging import create_logger
        logger = create_logger(name='mlcomp_tpu_greptest')
        lines = []

        class Capture(logging.Handler):
            def emit(self, record):
                lines.append(self.format(record))

        cap = Capture()
        cap.setFormatter(logging.Formatter(
            '%(module)s:%(lineno)d%(trace)s %(message)s'))
        logger.addHandler(cap)
        tid = new_trace_id()
        try:
            set_trace_context(tid, 'worker')
            logger.info('claimed task 7')
            logger.error('task 7 failed')
        finally:
            set_trace_context(None)
            logger.removeHandler(cap)
        hits = [ln for ln in lines if tid in ln]
        assert len(hits) == 2


class TestProfilerEdgeCases:
    """Satellite: the injectable-tracer lifecycle paths that were
    untested — a failing tracer, a sessionless profiler, polling
    after done."""

    def test_tracer_start_failure_writes_failed_status(self, session,
                                                       tmp_path):
        task = make_task(session)

        def boom(d):
            raise RuntimeError('no backend')

        prof = TaskProfiler(session, task.id, str(tmp_path),
                            tracer_start=boom,
                            tracer_stop=lambda: None)
        request_trace(session, task.id)
        assert prof.poll() is False
        assert prof.tracing is False
        status = trace_status(session, task.id)
        assert status['status'] == 'failed'
        assert 'no backend' in status['error']

    def test_sessionless_profiler_is_inert(self, tmp_path):
        prof = TaskProfiler(None, 1, str(tmp_path),
                            tracer_start=lambda d: None,
                            tracer_stop=lambda: None)
        assert prof.poll() is False
        prof.close()                    # must not raise

    def test_poll_after_done_stays_off(self, session, tmp_path):
        task = make_task(session)
        calls = []
        prof = TaskProfiler(session, task.id, str(tmp_path),
                            tracer_start=lambda d: calls.append('s'),
                            tracer_stop=lambda: calls.append('e'))
        request_trace(session, task.id, max_epochs=1)
        assert prof.poll() is True
        assert prof.poll() is False     # max_epochs expired → done
        assert prof.poll() is False     # done row does NOT restart
        assert calls == ['s', 'e']

class TestAttributionInRealRun:
    def test_jax_train_persists_phase_and_efficiency_series(
            self, session, tmp_path):
        """Acceptance: a real jax_train run records step.phase.* for
        every step and a per-epoch step.pipeline_efficiency gauge —
        bench's number, from inside production."""
        from mlcomp_tpu.train import JaxTrain

        class DummyStep:
            def start(self, *a, **k):
                pass

            def info(self, m):
                pass

            def debug(self, m):
                pass

            def error(self, m):
                pass

            def end_all(self):
                pass

        task = make_task(session)
        ex = JaxTrain(
            model={'name': 'mlp', 'hidden': [16], 'num_classes': 4},
            dataset={'name': 'synthetic_images', 'n_train': 256,
                     'n_valid': 64, 'image_size': 8, 'channels': 1,
                     'num_classes': 4},
            loss='softmax_ce', batch_size=32, epochs=2,
            telemetry={'flush_every': 16},
            checkpoint_dir=str(tmp_path / 'ck'))
        ex.step = DummyStep()
        ex.task = task
        ex.dag = DagProvider(session).by_id(task.dag)
        ex.session = session
        ex.additional_info = {}
        ex.work()

        series = MetricProvider(session).series(task_id=task.id)
        # 2 epochs x 8 steps of per-step phase attribution
        for phase in ('data_wait', 'h2d', 'compute', 'telemetry'):
            pts = series[f'step.phase.{phase}_ms']
            assert len(pts) == 16, phase
            assert all(p['value'] >= 0 for p in pts)
        eff = series['step.pipeline_efficiency']
        assert [p['step'] for p in eff] == [0, 1]   # one per epoch
        assert all(0.0 < p['value'] <= 1.0 for p in eff)
        # the compile listener saw the first-step compiles (skipped
        # quietly if this jax build has no monitoring hooks)
        from mlcomp_tpu.telemetry import CompileEventRecorder
        if CompileEventRecorder().install():
            assert 'compile.backend_ms' in series
