"""Sharded checkpoint I/O (train/ckpt_shard.py): per-host shard files,
no full-state materialization, resharding restore across mesh shapes.

VERDICT r4 weak #2: the msgpack path gathered the FULL replicated state
onto every host before rank-0 wrote — un-doing fsdp exactly when it
matters. These tests pin the fix: save/restore buffer sizes stay
shard-sized on an fsdp mesh, and a checkpoint saved under one mesh
shape restores onto another (reference resume semantics,
reference worker/executors/catalyst/catalyst.py:218-296, at TPU scale).
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from mlcomp_tpu.train import checkpoint as ck  # noqa: E402
from mlcomp_tpu.train import ckpt_shard as cs  # noqa: E402


def _mesh(shape, names):
    devs = np.array(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def _state(mesh, spec_w, n=1024, k=256, seed=0):
    """A state-dict-shaped pytree: fsdp-sharded weights + replicated
    scalar step (like a real TrainState's flattened form)."""
    key = jax.random.PRNGKey(seed)
    w = jax.random.normal(key, (n, k), jnp.float32)
    b = jax.random.normal(jax.random.fold_in(key, 1), (n,), jnp.float32)
    return {
        'params': {
            'w': jax.device_put(w, NamedSharding(mesh, spec_w)),
            'b': jax.device_put(b, NamedSharding(mesh, P())),
        },
        'step': jax.device_put(jnp.asarray(7, jnp.int32),
                               NamedSharding(mesh, P())),
    }


def _zeros_like_placed(state, mesh, spec_w):
    return {
        'params': {
            'w': jax.device_put(
                jnp.zeros_like(state['params']['w']),
                NamedSharding(mesh, spec_w)),
            'b': jax.device_put(
                jnp.zeros_like(state['params']['b']),
                NamedSharding(mesh, P())),
        },
        'step': jax.device_put(jnp.asarray(0, jnp.int32),
                               NamedSharding(mesh, P())),
    }


def test_fsdp_save_restore_stays_shard_sized(tmp_path):
    mesh = _mesh((8,), ('fsdp',))
    state = _state(mesh, P('fsdp', None))
    full_w_bytes = 1024 * 256 * 4

    assert cs.state_needs_sharded_ckpt(state)
    cs.LAST_STATS['save_max_shard_bytes'] = 0
    cs.LAST_STATS['restore_max_buffer_bytes'] = 0
    cs.save_checkpoint_sharded(str(tmp_path), state,
                               {'step': 7, 'epoch': 0, 'score': 0.5})
    # no host buffer during save exceeded one shard of the big leaf
    assert cs.LAST_STATS['save_max_shard_bytes'] <= full_w_bytes // 8

    target = _zeros_like_placed(state, mesh, P('fsdp', None))
    restored, meta = ck.restore_checkpoint(str(tmp_path), target)
    assert meta['score'] == 0.5
    assert cs.LAST_STATS['restore_max_buffer_bytes'] <= full_w_bytes // 8
    np.testing.assert_array_equal(np.asarray(restored['params']['w']),
                                  np.asarray(state['params']['w']))
    np.testing.assert_array_equal(np.asarray(restored['params']['b']),
                                  np.asarray(state['params']['b']))
    assert int(restored['step']) == 7
    # arrays land already placed on the target's shardings
    assert restored['params']['w'].sharding == \
        target['params']['w'].sharding


def test_restore_onto_different_mesh_shape(tmp_path):
    mesh8 = _mesh((8,), ('fsdp',))
    state = _state(mesh8, P('fsdp', None), seed=3)
    cs.save_checkpoint_sharded(str(tmp_path), state, {'step': 1})

    # 4-device fsdp mesh: each restoring device's slice spans TWO saved
    # shards — the geometric assembly path
    mesh4 = _mesh((4,), ('fsdp',))
    target4 = _zeros_like_placed(state, mesh4, P('fsdp', None))
    restored4, _ = cs.restore_checkpoint_sharded(str(tmp_path), target4)
    np.testing.assert_array_equal(np.asarray(restored4['params']['w']),
                                  np.asarray(state['params']['w']))

    # 2x4 dp x fsdp mesh, sharded on the SECOND axis + replicated on dp
    mesh24 = _mesh((2, 4), ('dp', 'fsdp'))
    target24 = _zeros_like_placed(state, mesh24, P('fsdp', None))
    restored24, _ = cs.restore_checkpoint_sharded(str(tmp_path),
                                                  target24)
    np.testing.assert_array_equal(np.asarray(restored24['params']['w']),
                                  np.asarray(state['params']['w']))
    assert restored24['params']['w'].sharding == \
        target24['params']['w'].sharding


def test_best_copy_and_meta_dispatch(tmp_path):
    mesh = _mesh((8,), ('fsdp',))
    state = _state(mesh, P('fsdp', None), seed=5)
    cs.save_checkpoint_sharded(str(tmp_path), state,
                               {'step': 2, 'score': 0.9}, best=True)
    assert ck.checkpoint_exists(str(tmp_path), 'best') == \
        os.path.join(str(tmp_path), 'best')
    meta = ck.load_meta(str(tmp_path), 'best')
    assert meta['score'] == 0.9
    target = _zeros_like_placed(state, mesh, P('fsdp', None))
    restored, _ = ck.restore_checkpoint(str(tmp_path), target,
                                        kind='best')
    np.testing.assert_array_equal(np.asarray(restored['params']['w']),
                                  np.asarray(state['params']['w']))


def test_torn_save_keeps_previous_generation(tmp_path):
    mesh = _mesh((8,), ('fsdp',))
    s1 = _state(mesh, P('fsdp', None), seed=1)
    cs.save_checkpoint_sharded(str(tmp_path), s1, {'step': 1})
    s2 = _state(mesh, P('fsdp', None), seed=2)
    # crash mid-save: fragments of the next generation land, index
    # never flips — restore must still see generation 1 intact
    folder = os.path.join(str(tmp_path), 'last')
    cs._write_fragment(folder, 2, 0, cs.build_shard_plan(s2))
    target = _zeros_like_placed(s1, mesh, P('fsdp', None))
    restored, meta = ck.restore_checkpoint(str(tmp_path), target)
    assert meta['step'] == 1
    np.testing.assert_array_equal(np.asarray(restored['params']['w']),
                                  np.asarray(s1['params']['w']))
    # the NEXT completed save cleans the orphaned generation
    cs.save_checkpoint_sharded(str(tmp_path), s2, {'step': 3})
    names = sorted(os.listdir(folder))
    assert not any('-g1-' in n or '-g2-' in n for n in names), names


def test_generation_cleanup_and_overwrite(tmp_path):
    mesh = _mesh((8,), ('fsdp',))
    for step, seed in ((1, 1), (2, 2), (3, 9)):
        st = _state(mesh, P('fsdp', None), seed=seed)
        cs.save_checkpoint_sharded(str(tmp_path), st, {'step': step})
    folder = os.path.join(str(tmp_path), 'last')
    frag_files = [n for n in os.listdir(folder) if n.startswith('shards')]
    assert len(frag_files) == 2        # one npz + one json, latest gen
    assert all('-g3-' in n for n in frag_files)
    target = _zeros_like_placed(st, mesh, P('fsdp', None))
    restored, meta = ck.restore_checkpoint(str(tmp_path), target)
    assert meta['step'] == 3
    np.testing.assert_array_equal(np.asarray(restored['params']['w']),
                                  np.asarray(st['params']['w']))


def test_structure_mismatch_raises(tmp_path):
    mesh = _mesh((8,), ('fsdp',))
    state = _state(mesh, P('fsdp', None))
    cs.save_checkpoint_sharded(str(tmp_path), state, {'step': 1})
    target = _zeros_like_placed(state, mesh, P('fsdp', None))
    target['params']['extra'] = target['params']['b']
    with pytest.raises(ValueError, match='structure mismatch'):
        cs.restore_checkpoint_sharded(str(tmp_path), target)


def test_untyped_full_read_for_export(tmp_path):
    mesh = _mesh((8,), ('fsdp',))
    state = _state(mesh, P('fsdp', None), seed=11)
    cs.save_checkpoint_sharded(str(tmp_path), state, {'step': 4})
    tree = cs.read_checkpoint_tree(os.path.join(str(tmp_path), 'last'))
    np.testing.assert_array_equal(tree['params']['w'],
                                  np.asarray(state['params']['w']))
    assert tree['step'] == 7    # the state leaf, not the meta


def test_bfloat16_round_trip(tmp_path):
    """ml_dtypes arrays degrade to void under plain np.savez — the
    fragment writer stores them as bit-identical uint views and the
    reader views back via the index's recorded dtype."""
    mesh = _mesh((8,), ('fsdp',))
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 64),
                          jnp.bfloat16)
    state = {'params': {'w': jax.device_put(
        w, NamedSharding(mesh, P('fsdp', None)))}}
    cs.save_checkpoint_sharded(str(tmp_path), state, {'step': 1})
    target = {'params': {'w': jax.device_put(
        jnp.zeros_like(w), NamedSharding(mesh, P('fsdp', None)))}}
    restored, _ = cs.restore_checkpoint_sharded(str(tmp_path), target)
    assert restored['params']['w'].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored['params']['w']).view(np.uint16),
        np.asarray(w).view(np.uint16))
    tree = cs.read_checkpoint_tree(os.path.join(str(tmp_path), 'last'))
    assert tree['params']['w'].dtype == jnp.bfloat16


def test_orphan_rank_fragments_filtered_and_reaped(tmp_path):
    """A restart with fewer processes + a colliding step-derived
    generation must not merge a dead rank's stale shards into reads."""
    mesh = _mesh((8,), ('fsdp',))
    state = _state(mesh, P('fsdp', None), seed=4)
    cs.save_checkpoint_sharded(str(tmp_path), state, {'step': 5})
    folder = os.path.join(str(tmp_path), 'last')
    # forge fragments from a phantom rank 1 of an earlier, wider run
    # (same generation number)
    import shutil as _sh
    for ext in ('.npz', '.json'):
        _sh.copyfile(os.path.join(folder, f'shards-g5-p00000{ext}'),
                     os.path.join(folder, f'shards-g5-p00001{ext}'))
    # reader must ignore ranks >= index nprocs (1)
    target = _zeros_like_placed(state, mesh, P('fsdp', None))
    restored, _ = ck.restore_checkpoint(str(tmp_path), target)
    np.testing.assert_array_equal(np.asarray(restored['params']['w']),
                                  np.asarray(state['params']['w']))
    tree = cs.read_checkpoint_tree(folder)   # require_all path too
    np.testing.assert_array_equal(tree['params']['w'],
                                  np.asarray(state['params']['w']))
    # the next save's rank-0 cleanup reaps the orphans outright
    cs.save_checkpoint_sharded(str(tmp_path), state, {'step': 6})
    assert not any('p00001' in n for n in os.listdir(folder))


def test_stale_blob_does_not_shadow_newer_sharded(tmp_path):
    """Crash window: sharded index committed, stale msgpack not yet
    removed — dispatch must pick whichever meta is NEWER."""
    import json as _json
    mesh = _mesh((8,), ('fsdp',))
    state = _state(mesh, P('fsdp', None), seed=8)
    cs.save_checkpoint_sharded(str(tmp_path), state,
                               {'step': 9, 'score': 0.7})
    # forge an OLDER flat blob that a crash failed to clean up
    blob = os.path.join(str(tmp_path), 'last.msgpack')
    with open(blob, 'wb') as fh:
        fh.write(b'stale')
    with open(blob + '.meta.json', 'w') as fh:
        _json.dump({'step': 1, 'score': 0.1, 'time': 100.0}, fh)
    assert ck.checkpoint_exists(str(tmp_path)) == \
        os.path.join(str(tmp_path), 'last')
    assert ck.load_meta(str(tmp_path))['score'] == 0.7
    target = _zeros_like_placed(state, mesh, P('fsdp', None))
    restored, meta = ck.restore_checkpoint(str(tmp_path), target)
    assert meta['score'] == 0.7
    # and the reverse: a NEWER blob wins over an older sharded dir
    with open(blob + '.meta.json', 'w') as fh:
        _json.dump({'step': 99, 'time': 1e12}, fh)
    assert ck.checkpoint_exists(str(tmp_path)) == blob


def test_replicated_state_keeps_msgpack_format():
    mesh = _mesh((8,), ('dp',))
    state = _state(mesh, P())     # fully replicated: dp-only training
    assert not cs.state_needs_sharded_ckpt(state)
