"""cc-lock-order clean twin: both paths acquire source-then-sink."""

import threading


class Router:
    def __init__(self):
        self.source_lock = threading.Lock()
        self.sink_lock = threading.Lock()
        self.moved = 0

    def transfer(self):
        with self.source_lock:
            with self.sink_lock:
                self.moved += 1

    def rebalance(self):
        with self.source_lock:
            with self.sink_lock:
                self.moved += 1
