"""db-naked-transition positive: both shapes — a raw UPDATE that sets
a state column without checking its prior value, and an ORM-style
write shipped through an unconditional ``update(obj)``."""


class LeaseProvider:
    def __init__(self, session):
        self.session = session

    def finish(self, lease_id: int):
        # lost-update: a reclaimed-and-reclaimed lease is overwritten
        self.session.execute(
            "UPDATE lease SET status='done' WHERE id=?", (lease_id,))

    def mark_unhealthy(self, replica):
        replica.state = 'unhealthy'
        self.update(replica, ['state'])

    def update(self, obj, fields):
        self.session.update_obj(obj, fields)
