"""cc-lockset positive: ``pending`` is written under ``self.lock`` on
the admit path but decremented with no lock on the release path, and
the admission check reads it outside the lock (check-then-act)."""

import threading


class Admission:
    def __init__(self):
        self.lock = threading.Lock()
        self.pending = 0
        self.limit = 4

    def admit(self) -> bool:
        if self.pending >= self.limit:       # unguarded check
            return False
        with self.lock:
            self.pending += 1
        return True

    def release(self):
        self.pending -= 1                    # unguarded write
