"""cc-lock-order positive: the transfer path takes source-then-sink,
the rebalance path takes sink-then-source — two concurrent callers
deadlock, each holding what the other wants."""

import threading


class Router:
    def __init__(self):
        self.source_lock = threading.Lock()
        self.sink_lock = threading.Lock()
        self.moved = 0

    def transfer(self):
        with self.source_lock:
            with self.sink_lock:
                self.moved += 1

    def rebalance(self):
        with self.sink_lock:
            with self.source_lock:
                self.moved += 1
