"""db-rmw-commit clean twin: the write happens before any other
statement intervenes — read, mutate, write back, then audit."""


class RetryPass:
    def __init__(self, session):
        self.session = session

    def bump_attempt(self, task_id: int):
        task = self.session.query_one(
            'SELECT * FROM task WHERE id=?', (task_id,))
        task.attempt = (task.attempt or 0) + 1
        self.update(task, ['attempt'])
        self.session.execute(
            'INSERT INTO audit (task) VALUES (?)', (task_id,))

    def update(self, obj, fields):
        self.session.update_obj(obj, fields)
