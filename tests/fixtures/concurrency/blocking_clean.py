"""cc-lock-held-blocking clean twin: the round-trip happens OUTSIDE
the lock; only the verdict write holds it."""

import threading
import time
import urllib.request


class Prober:
    def __init__(self):
        self.lock = threading.Lock()
        self.healthy = {}

    def probe(self, name: str, url: str):
        try:
            urllib.request.urlopen(url, timeout=2)
            ok = True
        except OSError:
            time.sleep(1.0)
            ok = False
        with self.lock:
            self.healthy[name] = ok
