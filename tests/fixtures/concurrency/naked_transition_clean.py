"""db-naked-transition clean twin: the transition is conditioned on
the prior value and the rowcount decides who won."""


class LeaseProvider:
    def __init__(self, session):
        self.session = session

    def finish(self, lease_id: int) -> bool:
        cur = self.session.execute(
            "UPDATE lease SET status='done' "
            "WHERE id=? AND status='claimed'", (lease_id,))
        return cur.rowcount > 0

    def mark_unhealthy(self, replica_id: int) -> bool:
        cur = self.session.execute(
            "UPDATE replica SET state='unhealthy' "
            "WHERE id=? AND state='healthy'", (replica_id,))
        return cur.rowcount > 0
