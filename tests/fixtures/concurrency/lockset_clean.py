"""cc-lockset clean twin: every access of ``pending`` — the check, the
increment, the decrement — holds ``self.lock``."""

import threading


class Admission:
    def __init__(self):
        self.lock = threading.Lock()
        self.pending = 0
        self.limit = 4

    def admit(self) -> bool:
        with self.lock:
            if self.pending >= self.limit:
                return False
            self.pending += 1
            return True

    def release(self):
        with self.lock:
            self.pending -= 1
