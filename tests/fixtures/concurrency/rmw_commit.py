"""db-rmw-commit positive: a row is read, another statement commits
(every statement is its own transaction), then the stale object is
mutated and written back — whatever a concurrent writer did to the
row in between is silently overwritten."""


class RetryPass:
    def __init__(self, session):
        self.session = session

    def bump_attempt(self, task_id: int):
        task = self.session.query_one(
            'SELECT * FROM task WHERE id=?', (task_id,))
        self.session.execute(
            'INSERT INTO audit (task) VALUES (?)', (task_id,))
        task.attempt = (task.attempt or 0) + 1
        self.update(task, ['attempt'])

    def update(self, obj, fields):
        self.session.update_obj(obj, fields)
