"""cc-lock-held-blocking positive: a health probe's HTTP round-trip
and its retry sleep both run inside the routing-table lock — every
request thread needing the table stalls behind the slowest endpoint."""

import threading
import time
import urllib.request


class Prober:
    def __init__(self):
        self.lock = threading.Lock()
        self.healthy = {}

    def probe(self, name: str, url: str):
        with self.lock:
            try:
                urllib.request.urlopen(url, timeout=2)
                self.healthy[name] = True
            except OSError:
                time.sleep(1.0)
                self.healthy[name] = False
