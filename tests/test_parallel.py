"""Mesh/sharding/ring-attention tests on the 8-device CPU-emulated mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlcomp_tpu.parallel import (
    batch_sharding, make_ring_attention, mesh_from_spec,
    normalize_mesh_spec, single_device_mesh,
)
from mlcomp_tpu.parallel.ring import _plain_attention


def test_normalize_mesh_spec_wildcard():
    assert normalize_mesh_spec({'dp': -1, 'tp': 2}, 8) == {'dp': 4, 'tp': 2}
    assert normalize_mesh_spec({'dp': 8}, 8) == {'dp': 8}
    assert normalize_mesh_spec(None, 8) == {'dp': 8}


def test_normalize_mesh_spec_errors():
    with pytest.raises(ValueError):
        normalize_mesh_spec({'dp': 3}, 8)
    with pytest.raises(ValueError):
        normalize_mesh_spec({'dp': -1, 'tp': -1}, 8)
    with pytest.raises(ValueError):
        normalize_mesh_spec({'bogus': 8}, 8)


def test_mesh_axis_order():
    mesh = mesh_from_spec({'tp': 2, 'dp': 2, 'sp': 2})
    assert mesh.axis_names == ('dp', 'sp', 'tp')  # canonical order
    assert mesh.devices.shape == (2, 2, 2)


def test_single_device_mesh_has_all_axes():
    mesh = single_device_mesh()
    assert set(mesh.axis_names) == {'dp', 'fsdp', 'ep', 'pp', 'sp', 'tp'}


def test_batch_sharding_spec():
    mesh = mesh_from_spec({'dp': 2, 'sp': 2, 'tp': 2})
    s = batch_sharding(mesh, ndim=2, seq_dim=1)
    assert s.spec == jax.sharding.PartitionSpec('dp', 'sp')


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('spec', [{'sp': 4, 'dp': 2}, {'sp': 8}])
def test_ring_attention_matches_plain(causal, spec):
    mesh = mesh_from_spec(spec)
    rng = np.random.RandomState(0)
    b, t, h, d = 2, 32, 4, 8
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)

    ring = make_ring_attention(mesh, causal=causal)
    with mesh:
        got = jax.jit(ring)(q, k, v)
    want = _plain_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grads_match():
    mesh = mesh_from_spec({'sp': 4, 'dp': 2})
    rng = np.random.RandomState(1)
    b, t, h, d = 2, 16, 2, 4
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    ring = make_ring_attention(mesh, causal=True)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_plain(q, k, v):
        return jnp.sum(_plain_attention(q, k, v, causal=True) ** 2)

    with mesh:
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    for gr, gp in zip(g_ring, g_plain):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gp),
                                   atol=5e-5, rtol=5e-5)
