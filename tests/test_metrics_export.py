"""OpenMetrics export tests: renderer↔parser round trip, parser
rejections, the live GET /metrics endpoint covering every required
family, and the serving-bucket re-export path."""

import json
import os
import urllib.request

import pytest

from mlcomp_tpu.db.enums import TaskStatus
from mlcomp_tpu.db.models import Computer, Dag, Task
from mlcomp_tpu.db.providers import (
    AlertProvider, ComputerProvider, DagProvider, MetricProvider,
    ProjectProvider, QueueProvider, TaskProvider,
)
from mlcomp_tpu.telemetry import MetricRecorder
from mlcomp_tpu.telemetry.export import (
    OPENMETRICS_CONTENT_TYPE, REQUIRED_FAMILIES, family,
    parse_openmetrics, render_openmetrics, render_server_metrics,
)
from mlcomp_tpu.utils.misc import now

from tests.test_telemetry import api  # noqa: F401  (live-server fixture)


def make_task(session, name='t', status=TaskStatus.InProgress,
              computer=None, cores=None):
    provider = ProjectProvider(session)
    project = provider.by_name('p_metrics')
    if project is None:
        provider.add_project('p_metrics')
        project = provider.by_name('p_metrics')
    dag = Dag(name='d', project=project.id, config='', created=now(),
              docker_img='default')
    DagProvider(session).add(dag)
    task = Task(name=name, executor='e', dag=dag.id,
                status=int(status), computer_assigned=computer,
                cores_assigned=json.dumps(cores) if cores else None,
                started=now(), last_activity=now())
    TaskProvider(session).add(task)
    return task


class TestRenderer:
    def test_round_trip(self):
        families = [
            family('mlcomp_up', 'gauge', 'liveness', [('', None, 1)]),
            family('mlcomp_tasks', 'gauge', 'by status',
                   [('', {'status': 'in_progress'}, 3),
                    ('', {'status': 'failed'}, 0)]),
            family('mlcomp_requests', 'counter', 'served',
                   [('_total', {'model': 'm'}, 12)]),
            family('mlcomp_lat', 'histogram', 'latency',
                   [('_bucket', {'le': 5.0}, 2),
                    ('_bucket', {'le': '+Inf'}, 4),
                    ('_count', None, 4), ('_sum', None, 17.5)]),
        ]
        text = render_openmetrics(families)
        assert text.endswith('# EOF\n')
        doc = parse_openmetrics(text)
        assert doc['mlcomp_up']['type'] == 'gauge'
        assert doc['mlcomp_up']['help'] == 'liveness'
        assert doc['mlcomp_tasks']['samples'] == [
            ('mlcomp_tasks', {'status': 'in_progress'}, 3.0),
            ('mlcomp_tasks', {'status': 'failed'}, 0.0)]
        assert doc['mlcomp_requests']['samples'][0][0] == \
            'mlcomp_requests_total'
        lat = doc['mlcomp_lat']['samples']
        assert ('mlcomp_lat_bucket', {'le': '+Inf'}, 4.0) in lat
        assert ('mlcomp_lat_sum', {}, 17.5) in lat

    def test_label_escaping_round_trips(self):
        nasty = 'a"b\\c\nd'
        text = render_openmetrics(
            [family('m', 'gauge', 'h', [('', {'k': nasty}, 1)])])
        (sample,) = parse_openmetrics(text)['m']['samples']
        assert sample[1]['k'] == nasty

    def test_backslash_n_literal_round_trips(self):
        # 'weights\net1' (a literal backslash then 'n') must NOT decode
        # as a newline: unescaping is a single left-to-right scan
        nasty = 'weights\\net1'
        text = render_openmetrics(
            [family('m', 'gauge', 'h', [('', {'k': nasty}, 1)])])
        (sample,) = parse_openmetrics(text)['m']['samples']
        assert sample[1]['k'] == nasty
        assert '\n' not in sample[1]['k']

    def test_empty_family_renders_header_only(self):
        text = render_openmetrics(
            [family('mlcomp_queue_depth', 'gauge', 'depth')])
        doc = parse_openmetrics(text)
        assert doc['mlcomp_queue_depth']['samples'] == []


class TestParserRejections:
    def test_missing_eof(self):
        with pytest.raises(ValueError, match='EOF'):
            parse_openmetrics('# TYPE m gauge\nm 1\n')

    def test_undeclared_family(self):
        with pytest.raises(ValueError, match='no declared family'):
            parse_openmetrics('# TYPE m gauge\nother 1\n# EOF\n')

    def test_bad_value(self):
        with pytest.raises(ValueError, match='bad value'):
            parse_openmetrics('# TYPE m gauge\nm up\n# EOF\n')

    def test_garbage_line(self):
        with pytest.raises(ValueError, match='unparsable'):
            parse_openmetrics('# TYPE m gauge\n}{ nope\n# EOF\n')

    def test_content_after_eof(self):
        with pytest.raises(ValueError, match='after # EOF'):
            parse_openmetrics('# TYPE m gauge\n# EOF\nm 1\n')

    def test_malformed_label_block_rejected(self):
        # findall-style parsing would return zero labels and pass —
        # the validator must reject what a real scraper rejects
        with pytest.raises(ValueError, match='malformed label'):
            parse_openmetrics(
                '# TYPE m gauge\nm{le=+Inf, bad} 4\n# EOF\n')
        with pytest.raises(ValueError, match='malformed label'):
            parse_openmetrics(
                '# TYPE m gauge\nm{k="v" j="w"} 4\n# EOF\n')


def seed_everything(session):
    """One of each signal the collectors read."""
    ComputerProvider(session).create_or_update(
        Computer(name='box', cpu=8, memory=16, cores=4,
                 ip='127.0.0.1', port=22), 'name')
    task = make_task(session, computer='box', cores=[0, 1])
    QueueProvider(session).enqueue(
        'box_default', {'action': 'execute', 'task_id': task.id})
    AlertProvider(session).raise_alert(
        'hbm-pressure', 'high', task=task.id, severity='critical')
    ts = now()
    MetricProvider(session).add_many(
        [(task.id, f'step.phase.{p}_ms', 'series', 5, v, ts, 'train',
          None) for p, v in (('data_wait', 2.0), ('h2d', 1.0),
                             ('compute', 20.0), ('telemetry', 0.2))]
        + [(task.id, 'step.pipeline_efficiency', 'gauge', 0, 0.86,
            ts, 'train', None),
           (task.id, 'compile.backend_ms', 'series', 30, 140.0, ts,
            'train', None),
           (None, 'supervisor.dispatch_latency_s.p50', 'histogram',
            None, 0.3, ts, 'supervisor', None),
           (None, 'supervisor.dispatch_latency_s.p99', 'histogram',
            None, 0.9, ts, 'supervisor', None),
           (None, 'supervisor.dispatch_latency_s.count', 'histogram',
            None, 4.0, ts, 'supervisor', None),
           (None, 'supervisor.dispatch_latency_s.mean', 'histogram',
            None, 0.5, ts, 'supervisor', None)])
    # device-time attribution through the real path: the parsed
    # fixture window persisted exactly as the sampled engine does
    from mlcomp_tpu.telemetry.deviceprof import persist_attribution
    from mlcomp_tpu.telemetry.trace_parse import parse_trace_file
    persist_attribution(
        session, task.id,
        parse_trace_file(os.path.join(
            os.path.dirname(__file__), 'fixtures',
            'mini_device_trace.json.gz')), step=10)
    # serving buckets arrive through the REAL path: a bucketed
    # recorder flush, exactly what ModelServer's heartbeat does
    rec = MetricRecorder(session=session, component='serving',
                         flush_every=10 ** 9)
    for ms in (2.0, 8.0, 40.0, 900.0):
        rec.observe('serving.digits.latency_ms', ms,
                    buckets=(5.0, 50.0, 500.0))
    rec.flush()
    return task


class TestServerCollector:
    def test_all_required_families_present_even_on_empty_db(
            self, session):
        doc = parse_openmetrics(render_server_metrics(session))
        for fam in REQUIRED_FAMILIES:
            assert fam in doc, fam
        # empty DB: zero scrape errors, task counts all zero
        assert doc['mlcomp_scrape_errors']['samples'][0][2] == 0
        assert all(v == 0 for _, _, v in
                   doc['mlcomp_tasks']['samples'])

    def test_seeded_db_covers_the_acceptance_list(self, session):
        task = seed_everything(session)
        doc = parse_openmetrics(render_server_metrics(session))
        by = {f: doc[f]['samples'] for f in doc}
        assert ('mlcomp_queue_depth', {'queue': 'box_default'}, 1.0) \
            in by['mlcomp_queue_depth']
        assert any(l == {'status': 'in_progress'} and v == 1
                   for _, l, v in by['mlcomp_tasks'])
        slots = {(l['computer'], l['state']): v
                 for _, l, v in by['mlcomp_worker_slots']}
        assert slots[('box', 'total')] == 4
        assert slots[('box', 'busy')] == 2
        assert any(l.get('rule') == 'hbm-pressure'
                   and l.get('severity') == 'critical'
                   for _, l, v in by['mlcomp_alerts_open'])
        lat = {l.get('quantile'): v for n, l, v in
               by['mlcomp_dispatch_latency_seconds'] if l}
        assert lat['0.5'] == pytest.approx(0.3)
        assert lat['0.99'] == pytest.approx(0.9)
        # quantiles ONLY: the source summaries reset per flush window,
        # so a _count/_sum here would decrease between scrapes and
        # read as counter resets
        assert not any(n.endswith(('_count', '_sum')) for n, _, _ in
                       by['mlcomp_dispatch_latency_seconds'])
        phases = {(str(l['task']), l['phase']): v
                  for _, l, v in by['mlcomp_step_phase_ms']}
        assert phases[(str(task.id), 'compute')] == pytest.approx(20.0)
        assert len(phases) == 4
        (eff,) = by['mlcomp_pipeline_efficiency']
        assert eff[2] == pytest.approx(0.86)
        assert ('mlcomp_compile_events_total',
                {'task': str(task.id)}, 1.0) \
            in by['mlcomp_compile_events']
        devms = {l['bucket']: v for _, l, v in by['mlcomp_devtime_ms']
                 if l['task'] == str(task.id)}
        assert devms['compute'] == pytest.approx(1.3)
        assert devms['comm_exposed'] == pytest.approx(0.5)
        assert set(devms) == {'compute', 'comm', 'comm_exposed',
                              'io', 'idle'}
        (exp,) = by['mlcomp_devtime_exposed_comm_fraction']
        assert exp[1] == {'task': str(task.id)}
        assert exp[2] == pytest.approx(0.5 / 1.1, abs=1e-4)
        buckets = {l['le']: v for n, l, v in
                   by['mlcomp_serving_latency_ms']
                   if n.endswith('_bucket')}
        assert buckets['5.0'] == 1      # 2.0 only
        assert buckets['500.0'] == 3    # +8, +40
        assert buckets['+Inf'] == 4     # +900
        assert doc['mlcomp_scrape_errors']['samples'][0][2] == 0

    def test_finished_task_drops_out_of_phase_families(self, session):
        task = seed_everything(session)
        TaskProvider(session).change_status(task, TaskStatus.Success)
        doc = parse_openmetrics(render_server_metrics(session))
        assert doc['mlcomp_step_phase_ms']['samples'] == []
        assert doc['mlcomp_pipeline_efficiency']['samples'] == []
        assert doc['mlcomp_devtime_ms']['samples'] == []


class TestMetricsEndpoint:
    def _scrape(self, base):
        # the api fixture serves JSON; /metrics is text — fetch raw
        req = urllib.request.Request(base + '/metrics')
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.headers.get('Content-Type'), \
                resp.read().decode()

    def test_get_metrics_serves_valid_openmetrics(self, api, session):
        seed_everything(session)
        ctype, body = self._scrape(api.base)
        assert ctype == OPENMETRICS_CONTENT_TYPE
        doc = parse_openmetrics(body)
        for fam in REQUIRED_FAMILIES:
            assert fam in doc, fam
        assert doc['mlcomp_up']['samples'][0][2] == 1

    def test_metrics_needs_no_auth(self, api):
        # no Authorization header at all — same introspection tier as
        # the other telemetry reads
        req = urllib.request.Request(api.base + '/metrics')
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.status == 200


class TestCumulativeBucketSemantics:
    def test_bucketed_histograms_survive_flushes_monotone(
            self, session):
        """The API re-export promises Prometheus histogram semantics:
        flushed bucket rows must be cumulative (monotone) across
        flush windows, and an idle window must emit nothing."""
        rec = MetricRecorder(session=session, component='serving',
                             flush_every=10 ** 9)
        name = 'serving.m.latency_ms'
        rec.observe(name, 2.0, buckets=(5.0, 50.0))
        rec.observe(name, 8.0, buckets=(5.0, 50.0))
        rec.flush()
        rec.observe(name, 900.0)
        rec.observe(name, 1.0)
        rec.flush()
        rec.flush()                     # idle: no new rows
        rows = session.query(
            "SELECT id, value, tags FROM metric "
            "WHERE name='serving.m.latency_ms.bucket' ORDER BY id")
        inf_counts = [r['value'] for r in rows
                      if json.loads(r['tags'])['le'] == '+Inf']
        assert inf_counts == [2.0, 4.0]      # cumulative, idle silent
        # the collector re-exports the LATEST (largest) snapshot
        samples = []
        from mlcomp_tpu.telemetry.export import (
            _collect_serving_latency,
        )
        _collect_serving_latency(session, samples)
        buckets = {l['le']: v for n, l, v in samples
                   if n == '_bucket'}
        assert buckets['+Inf'] == 4.0
        assert buckets['5.0'] == 2.0         # 2.0 + 1.0
