"""Server-backed Session (multi-computer control plane, VERDICT round-1
item 6): providers run unchanged over the /api/db proxy — queue
claim/heartbeat round trips, blob integrity, token auth."""

import numpy as np
import pytest


@pytest.fixture()
def api(session):
    from mlcomp_tpu.server.api import ApiServer
    server = ApiServer(host='127.0.0.1', port=0).start_background()
    yield server
    server.shutdown()


@pytest.fixture()
def remote(api, session):
    from mlcomp_tpu.db.remote import RemoteSession
    return RemoteSession(f'http://127.0.0.1:{api.port}', key='remote')


class TestRemoteSession:
    def test_basic_roundtrip(self, remote):
        from mlcomp_tpu.db.models import Project
        from mlcomp_tpu.db.providers import ProjectProvider
        provider = ProjectProvider(remote)
        p = provider.add_project('remote_proj')
        assert p.id is not None
        got = provider.by_name('remote_proj')
        assert got is not None and got.id == p.id
        assert isinstance(got, Project)

    def test_blob_integrity(self, remote, session):
        """Code blobs survive the base64 proxy byte-for-byte."""
        from mlcomp_tpu.db.models import File
        from mlcomp_tpu.db.providers import FileProvider, ProjectProvider
        from mlcomp_tpu.utils.misc import now
        p = ProjectProvider(remote).add_project('remote_blob')
        payload = bytes(range(256)) * 10
        import hashlib
        f = File(md5=hashlib.md5(payload).hexdigest(), content=payload,
                 project=p.id, dag=None, created=now(), size=len(payload))
        FileProvider(remote).add(f)
        # read back through the LOCAL session: same bytes hit the disk
        row = session.query_one('SELECT content FROM file WHERE id=?',
                                (f.id,))
        assert bytes(row['content']) == payload
        # and back through the remote session
        row2 = remote.query_one('SELECT content FROM file WHERE id=?',
                                (f.id,))
        assert bytes(row2['content']) == payload

    def test_queue_claim_via_remote(self, remote, session):
        """The worker-side hot path: enqueue locally (supervisor),
        claim/complete remotely (worker on another computer)."""
        from mlcomp_tpu.db.providers import QueueProvider
        local_q = QueueProvider(session)
        remote_q = QueueProvider(remote)
        mid = local_q.enqueue('hostx_default', {'task_id': 42})
        claimed = remote_q.claim(['hostx_default'],
                                 worker='remote_worker')
        assert claimed is not None
        claimed_id, payload = claimed
        assert claimed_id == mid and payload['task_id'] == 42
        remote_q.complete(claimed_id)
        assert local_q.status(mid) == 'done'

    def test_heartbeat_via_remote(self, remote, session):
        from mlcomp_tpu.db.models import Computer
        from mlcomp_tpu.db.providers import ComputerProvider, DockerProvider
        ComputerProvider(remote).create_or_update(
            Computer(name='remote_host', cores=8, cpu=4, memory=8),
            'name')
        DockerProvider(remote).heartbeat('remote_host', 'default')
        row = session.query_one(
            "SELECT * FROM docker WHERE computer='remote_host'")
        assert row is not None

    def test_update_obj(self, remote):
        from mlcomp_tpu.db.providers import ProjectProvider
        provider = ProjectProvider(remote)
        p = provider.add_project('remote_edit')
        p.class_names = 'a,b,c'
        provider.update(p, ['class_names'])
        assert provider.by_id(p.id).class_names == 'a,b,c'

    def test_bad_token_rejected(self, api):
        from mlcomp_tpu.db.remote import RemoteSession
        bad = RemoteSession(f'http://127.0.0.1:{api.port}',
                            key='bad', token='wrong')
        import urllib.error
        with pytest.raises((urllib.error.HTTPError, RuntimeError),
                           match='401|unauthorized'):
            bad.query('SELECT 1 AS x')

    def test_create_session_routes_http(self, api):
        from mlcomp_tpu.db.core import Session
        from mlcomp_tpu.db.remote import RemoteSession
        s = Session.create_session(
            key='routed_remote',
            connection_string=f'http://127.0.0.1:{api.port}')
        try:
            assert isinstance(s, RemoteSession)
            assert s.query_one('SELECT 1 AS one')['one'] == 1
        finally:
            Session.cleanup('routed_remote')
