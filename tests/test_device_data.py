"""Device-resident input pipeline (bench honesty work, VERDICT item 4):
quantization, on-device augmentation, indexed/scanned train steps,
prefetch, cifar10 loader."""

import numpy as np
import pytest


class TestQuantize:
    def test_float01_packs_uint8(self):
        from mlcomp_tpu.train.device_data import quantize_dataset
        x = np.random.rand(10, 4, 4, 3).astype(np.float32)
        q, dq = quantize_dataset(x)
        assert q.dtype == np.uint8 and dq
        np.testing.assert_allclose(q / 255.0, x, atol=1 / 255)

    def test_uint8_passthrough(self):
        from mlcomp_tpu.train.device_data import quantize_dataset
        x = (np.random.rand(4, 2, 2, 3) * 255).astype(np.uint8)
        q, dq = quantize_dataset(x)
        assert q is x and dq

    def test_out_of_range_float_kept(self):
        from mlcomp_tpu.train.device_data import quantize_dataset
        x = np.random.randn(4, 2, 2, 3).astype(np.float32) * 10
        q, dq = quantize_dataset(x)
        assert q.dtype == np.float32 and not dq


class TestAugmentSpec:
    def test_device_expressible(self):
        from mlcomp_tpu.train.device_data import normalize_augment_spec
        spec = normalize_augment_spec(
            ['hflip', {'name': 'pad_crop', 'pad': 4}])
        assert spec == [('hflip', {}), ('pad_crop', {'pad': 4})]
        assert normalize_augment_spec(None) == []
        assert normalize_augment_spec(['transpose']) is None


class TestDeviceAugment:
    def test_shapes_and_determinism(self):
        import jax
        from mlcomp_tpu.train.device_data import make_device_augment
        aug = make_device_augment(
            [('pad_crop', {'pad': 2}), ('hflip', {}),
             ('cutout', {'size': 4})], (8, 8, 3))
        x = np.random.rand(6, 8, 8, 3).astype(np.float32)
        out1 = np.asarray(aug(x, jax.random.PRNGKey(0)))
        out2 = np.asarray(aug(x, jax.random.PRNGKey(0)))
        out3 = np.asarray(aug(x, jax.random.PRNGKey(1)))
        assert out1.shape == x.shape
        np.testing.assert_array_equal(out1, out2)
        assert not np.array_equal(out1, out3)

    def test_hflip_p1_flips_everything(self):
        import jax
        from mlcomp_tpu.train.device_data import make_device_augment
        aug = make_device_augment([('hflip', {'p': 1.0})], (4, 4, 3))
        x = np.random.rand(3, 4, 4, 3).astype(np.float32)
        out = np.asarray(aug(x, jax.random.PRNGKey(0)))
        np.testing.assert_allclose(out, x[:, :, ::-1, :])


def _clone(state):
    """Deep-copy device buffers — donating jits delete their inputs, so
    comparing two step variants needs independent states."""
    import jax.numpy as jnp
    import jax
    return jax.tree.map(lambda a: jnp.array(np.asarray(a))
                        if isinstance(a, jax.Array) else a, state)


class TestIndexedSteps:
    def _setup(self, mesh):
        import jax
        from mlcomp_tpu.models import create_model
        from mlcomp_tpu.train import (
            create_train_state, loss_for_task, make_optimizer,
        )
        model = create_model('mlp', num_classes=4, hidden=[16],
                             dtype='float32')
        opt, _ = make_optimizer({'name': 'sgd', 'lr': 0.1}, 100)
        x = np.random.rand(64, 4, 4, 1).astype(np.float32)
        y = np.random.randint(0, 4, 64).astype(np.int32)
        state = create_train_state(model, opt, x[:8],
                                   jax.random.PRNGKey(0), mesh=mesh)
        return model, opt, x, y, state, loss_for_task('softmax_ce')

    def test_device_step_matches_host_step(self):
        """Same batch, same params: indexed device step must produce the
        same loss as the host-batch step."""
        import jax
        from mlcomp_tpu.parallel import mesh_from_spec
        from mlcomp_tpu.parallel.sharding import batch_sharding
        from mlcomp_tpu.train import make_train_step
        from mlcomp_tpu.train.data import place_batch
        from mlcomp_tpu.train.device_data import place_dataset
        from mlcomp_tpu.train.loop import make_device_train_step

        mesh = mesh_from_spec({'dp': -1})
        model, opt, x, y, state, loss_fn = self._setup(mesh)
        state2 = _clone(state)

        host_step = make_train_step(model, opt, loss_fn, mesh=mesh)
        dev_step = make_device_train_step(model, opt, loss_fn, mesh=mesh)
        x_all, y_all = place_dataset(x, y, mesh)
        idx = np.arange(32, dtype=np.int32)

        xb, yb = place_batch((x[:32], y[:32]), mesh)
        _, m_host = host_step(state, xb, yb)
        _, m_dev = dev_step(
            state2, x_all, y_all,
            jax.device_put(idx, batch_sharding(mesh, 1)))
        assert float(m_host['loss']) == pytest.approx(
            float(m_dev['loss']), rel=1e-5)

    def test_epoch_scan_matches_stepwise(self):
        """lax.scan epoch == the same steps issued one by one."""
        import jax
        from mlcomp_tpu.parallel import mesh_from_spec
        from mlcomp_tpu.parallel.sharding import batch_sharding
        from mlcomp_tpu.train.device_data import place_dataset
        from mlcomp_tpu.train.loop import (
            make_device_epoch_fn, make_device_train_step,
        )

        mesh = mesh_from_spec({'dp': -1})
        model, opt, x, y, state, loss_fn = self._setup(mesh)
        state2 = _clone(state)
        x_all, y_all = place_dataset(x, y, mesh)
        perm = np.arange(64, dtype=np.int32).reshape(4, 16)

        dev_step = make_device_train_step(model, opt, loss_fn, mesh=mesh)
        step_losses = []
        st = state
        for s in range(4):
            st, m = dev_step(st, x_all, y_all,
                             jax.device_put(perm[s],
                                            batch_sharding(mesh, 1)))
            step_losses.append(float(m['loss']))

        epoch_fn = make_device_epoch_fn(model, opt, loss_fn, mesh=mesh)
        perm_dev = jax.device_put(
            perm, batch_sharding(mesh, 2, batch_dim=1))
        _, metrics = epoch_fn(state2, x_all, y_all, perm_dev)
        np.testing.assert_allclose(
            np.asarray(metrics['loss']), step_losses, rtol=1e-5)

    def test_dequantize_matches_float(self):
        import jax
        from mlcomp_tpu.parallel import mesh_from_spec
        from mlcomp_tpu.parallel.sharding import batch_sharding
        from mlcomp_tpu.train.device_data import (
            place_dataset, quantize_dataset,
        )
        from mlcomp_tpu.train.loop import make_device_train_step

        mesh = mesh_from_spec({'dp': -1})
        model, opt, x, y, state, loss_fn = self._setup(mesh)
        x = np.round(x * 255) / 255  # exactly representable
        state2 = _clone(state)
        idx = jax.device_put(np.arange(16, dtype=np.int32),
                             batch_sharding(mesh, 1))

        xf_all, y_all = place_dataset(x.astype(np.float32), y, mesh)
        plain = make_device_train_step(model, opt, loss_fn, mesh=mesh)
        _, m_f = plain(state, xf_all, y_all, idx)

        xq, dq = quantize_dataset(x)
        assert dq
        xq_all, y_all2 = place_dataset(xq, y, mesh)
        quant = make_device_train_step(model, opt, loss_fn, mesh=mesh,
                                       dequantize=True)
        _, m_q = quant(state2, xq_all, y_all2, idx)
        assert float(m_f['loss']) == pytest.approx(
            float(m_q['loss']), rel=1e-4)


class TestExecutorSelection:
    def test_jax_train_device_path_with_augment_runs(self, tmp_path):
        """auto path + on-device augmentation runs end to end (the
        synthetic iid-noise prototypes are NOT shift-invariant, so no
        accuracy bar here — test_train's test_mlp_learns covers learning
        through the same device path without augmentation)."""
        from test_train import DummyStep
        from mlcomp_tpu.train import JaxTrain
        ex = JaxTrain(
            model={'name': 'mlp', 'num_classes': 4, 'hidden': [32],
                   'dtype': 'float32'},
            dataset={'name': 'synthetic_images', 'n_train': 256,
                     'n_valid': 64, 'image_size': 8, 'channels': 1,
                     'num_classes': 4},
            batch_size=64, epochs=2,
            augment=[{'name': 'pad_crop', 'pad': 1}, 'hflip'],
            checkpoint_dir=str(tmp_path / 'ck'))
        ex.step = DummyStep()
        ex.task = None
        ex.session = None
        ex.additional_info = {}
        result = ex.work()
        assert result['best_score'] is not None
        assert np.isfinite(result['best_score'])

    def test_host_path_when_augment_not_device_expressible(self,
                                                           tmp_path):
        from test_train import DummyStep
        from mlcomp_tpu.train import JaxTrain
        ex = JaxTrain(
            model={'name': 'mlp', 'num_classes': 4, 'hidden': [16],
                   'dtype': 'float32'},
            dataset={'name': 'synthetic_images', 'n_train': 128,
                     'n_valid': 32, 'image_size': 8, 'channels': 1,
                     'num_classes': 4},
            batch_size=32, epochs=1,
            augment=['transpose'],     # not in DEVICE_AUGMENTS
            checkpoint_dir=str(tmp_path / 'ck'))
        ex.step = DummyStep()
        ex.task = None
        ex.session = None
        ex.additional_info = {}
        result = ex.work()
        assert result['best_score'] is not None

    def test_epoch_scan_option(self, tmp_path):
        from test_train import DummyStep
        from mlcomp_tpu.train import JaxTrain
        ex = JaxTrain(
            model={'name': 'mlp', 'num_classes': 4, 'hidden': [16],
                   'dtype': 'float32'},
            dataset={'name': 'synthetic_images', 'n_train': 128,
                     'n_valid': 32, 'image_size': 8, 'channels': 1,
                     'num_classes': 4},
            batch_size=32, epochs=2, epoch_scan=True,
            checkpoint_dir=str(tmp_path / 'ck'))
        ex.step = DummyStep()
        ex.task = None
        ex.session = None
        ex.additional_info = {}
        result = ex.work()
        assert result['best_score'] is not None


class TestDataHelpers:
    def test_prefetch_preserves_order_and_count(self):
        from mlcomp_tpu.parallel import mesh_from_spec
        from mlcomp_tpu.train.data import iterate_batches, prefetch_batches
        mesh = mesh_from_spec({'dp': -1})
        x = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        got = list(prefetch_batches(
            iterate_batches(x, None, 8), mesh))
        assert len(got) == 4
        np.testing.assert_array_equal(np.asarray(got[0][0]), x[:8])
        np.testing.assert_array_equal(np.asarray(got[-1][0]), x[24:])

    def test_iterate_batches_logs_dropped_tail(self):
        from mlcomp_tpu.train.data import iterate_batches
        messages = []
        list(iterate_batches(np.zeros((10, 2)), None, 4,
                             logger=messages.append))
        assert any('dropping 2 tail samples' in m for m in messages)

    def test_cifar10_loader_real_npz(self, tmp_path, monkeypatch):
        from mlcomp_tpu.train.data import create_dataset
        x = (np.random.rand(20, 32, 32, 3) * 255).astype(np.uint8)
        y = np.arange(20) % 10
        path = tmp_path / 'cifar10.npz'
        np.savez(path, x_train=x, y_train=y, x_test=x[:5], y_test=y[:5])
        monkeypatch.setenv('CIFAR10_NPZ', str(path))
        data = create_dataset('cifar10')
        assert data['source'] == str(path)
        assert data['x_train'].shape == (20, 32, 32, 3)
        assert data['x_train'].max() <= 1.0

    def test_cifar10_loader_synthetic_fallback(self):
        from mlcomp_tpu.train.data import create_dataset
        data = create_dataset('cifar10', n_train=64, n_valid=16)
        assert data['source'] == 'synthetic'
        assert data['x_train'].shape == (64, 32, 32, 3)


class TestAggregateMetrics:
    def test_mean_and_weighted(self):
        import jax.numpy as jnp
        from mlcomp_tpu.train.loop import aggregate_metrics
        ms = [{'loss': jnp.asarray(1.0), 'acc': jnp.asarray(0.5)},
              {'loss': jnp.asarray(3.0), 'acc': jnp.asarray(1.0)}]
        agg = aggregate_metrics(ms)
        assert agg == {'loss': 2.0, 'acc': 0.75}
        weighted = aggregate_metrics(ms, weights=[3, 1])
        assert weighted['loss'] == pytest.approx(1.5)
        assert aggregate_metrics([]) == {}


class TestDeviceEval:
    def test_device_eval_matches_host_eval(self):
        """Indexed HBM-resident eval == the host-batch eval step,
        including zero-weight tail padding."""
        import jax
        from mlcomp_tpu.models import create_model
        from mlcomp_tpu.parallel import mesh_from_spec
        from mlcomp_tpu.parallel.sharding import batch_sharding
        from mlcomp_tpu.train import (
            create_train_state, loss_for_task, make_optimizer,
        )
        from mlcomp_tpu.train.data import place_batch
        from mlcomp_tpu.train.device_data import place_dataset
        from mlcomp_tpu.train.loop import (
            make_device_eval_step, make_eval_step,
        )
        mesh = mesh_from_spec({'dp': -1})
        model = create_model('mlp', num_classes=4, hidden=[16],
                             dtype='float32')
        opt, _ = make_optimizer({'name': 'sgd', 'lr': 0.1}, 10)
        loss_fn = loss_for_task('softmax_ce')
        x = np.random.rand(20, 4, 4, 1).astype(np.float32)
        y = np.random.randint(0, 4, 20).astype(np.int32)
        state = create_train_state(model, opt, x[:8],
                                   jax.random.PRNGKey(0), mesh=mesh)
        x_all, y_all = place_dataset(x, y, mesh)
        # a padded tail batch: 4 real rows padded to 8, zero weights
        take = np.resize(np.arange(16, 20), 8)
        w = np.array([1, 1, 1, 1, 0, 0, 0, 0], np.float32)
        w_dev = jax.device_put(w, batch_sharding(mesh, 1))
        dev = make_device_eval_step(model, loss_fn, mesh=mesh)
        m_dev = dev(state, x_all, y_all,
                    jax.device_put(take.astype(np.int32),
                                   batch_sharding(mesh, 1)), w_dev)
        host = make_eval_step(model, loss_fn, mesh=mesh)
        xb, yb = place_batch((x[take], y[take]), mesh)
        m_host = host(state, xb, yb, w_dev)
        for k in m_host:
            assert float(m_dev[k]) == pytest.approx(float(m_host[k]),
                                                    rel=1e-6), k


class TestCheckpointCadence:
    def test_last_of_stage_always_saved(self, tmp_path):
        """Even with a huge checkpoint_every, the stage's final epoch
        writes `last` (resume/export depend on it)."""
        import os
        from test_train import DummyStep
        from mlcomp_tpu.train import JaxTrain
        from mlcomp_tpu.train.checkpoint import load_meta
        ex = JaxTrain(
            model={'name': 'mlp', 'num_classes': 4, 'hidden': [16],
                   'dtype': 'float32'},
            dataset={'name': 'synthetic_images', 'n_train': 128,
                     'n_valid': 32, 'image_size': 8, 'channels': 1,
                     'num_classes': 4},
            batch_size=32, epochs=3, checkpoint_every=1000,
            checkpoint_dir=str(tmp_path / 'ck'))
        ex.step = DummyStep()
        ex.task = None
        ex.session = None
        ex.additional_info = {}
        ex.work()
        assert os.path.exists(tmp_path / 'ck' / 'last.msgpack')
        meta = load_meta(str(tmp_path / 'ck'))
        assert meta['stage_epoch'] == 2  # the stage's FINAL epoch

    def test_resume_after_cadenced_run(self, tmp_path):
        from test_train import DummyStep
        from mlcomp_tpu.train import JaxTrain

        def run(epochs):
            ex = JaxTrain(
                model={'name': 'mlp', 'num_classes': 4, 'hidden': [16],
                       'dtype': 'float32'},
                dataset={'name': 'synthetic_images', 'n_train': 128,
                         'n_valid': 32, 'image_size': 8, 'channels': 1,
                         'num_classes': 4},
                batch_size=32, checkpoint_every=1000,
                stages=[{'name': 's1', 'epochs': epochs,
                         'optimizer': {'name': 'adam', 'lr': 3e-3}}],
                checkpoint_dir=str(tmp_path / 'ck'))
            ex.step = DummyStep()
            ex.task = None
            ex.session = None
            ex.additional_info = {}
            return ex.work()

        run(2)
        # re-run with more epochs: resumes past the 2 completed ones
        result = run(4)
        assert result['best_score'] is not None


def test_augment_wide_integer_pixels_exact():
    """Integer pixel data wider than 1 byte survives augmentation
    bit-exactly with its dtype preserved — the crop takes the native-
    dtype gather path (no float dtype could hold int32 > 2^24), not
    the bf16 MXU fast path reserved for 1-byte dtypes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mlcomp_tpu.train.device_data import make_device_augment

    x = jnp.asarray(np.random.RandomState(0).randint(
        0, 65536, (4, 8, 8, 1)), jnp.uint16)
    aug = make_device_augment([('hflip', {'p': 0.0})], (8, 8))
    out = aug(x, jax.random.PRNGKey(0))
    assert out.dtype == jnp.uint16
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    # pad_crop with zero displacement range is also an exact copy
    aug2 = make_device_augment([('pad_crop', {'pad': 0})], (8, 8))
    out2 = aug2(x, jax.random.PRNGKey(1))
    assert out2.dtype == jnp.uint16
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(x))

    # int32 beyond f32's 2^24 integer range: no float dtype could hold
    # these — the gather crop and dtype-agnostic flips must stay exact
    big = 2 ** 24 + 1
    xi = jnp.full((2, 8, 8, 1), big, jnp.int32)
    for spec in ([('hflip', {'p': 0.0})], [('pad_crop', {'pad': 2})],
                 [('cutout', {'size': 2, 'p': 0.0})]):
        oi = make_device_augment(spec, (8, 8))(xi, jax.random.PRNGKey(2))
        assert oi.dtype == jnp.int32
        assert int(oi.max()) == big and int(oi.min()) == big, spec
