"""Pipe DAGs + ModelAdd (VERDICT round-1 item 8): register a pipe,
add a trained model, start the pipe for it, run it end-to-end."""

import numpy as np
import pytest

from mlcomp_tpu.db.enums import DagType, TaskStatus
from mlcomp_tpu.db.providers import (
    DagProvider, ModelProvider, TaskProvider,
)
from mlcomp_tpu.server.create_dags import (
    dag_model_add, dag_model_start, dag_pipe, dag_standard,
)
from mlcomp_tpu.worker.tasks import execute_by_id

DATASET = {'name': 'synthetic_images', 'n_train': 256, 'n_valid': 64,
           'image_size': 8, 'channels': 1, 'num_classes': 4}

TRAIN_CONFIG = {
    'info': {'name': 'train_dag', 'project': 'p_pipes'},
    'executors': {
        'train': {
            'type': 'jax_train',
            'model': {'name': 'mlp', 'num_classes': 4, 'hidden': [32],
                      'dtype': 'float32'},
            'dataset': DATASET,
            'batch_size': 64,
            'stages': [{'name': 's1', 'epochs': 2,
                        'optimizer': {'name': 'adam', 'lr': 3e-3}}],
        },
    },
}

PIPE_CONFIG = {
    'info': {'name': 'serve_pipe', 'project': 'p_pipes'},
    'pipes': {
        'serve_pipe': {
            'infer': {
                'type': 'infer_classify',
                'dataset': DATASET,
                'batch_size': 64,
            },
            'valid': {
                'type': 'valid_classify',
                'dataset': DATASET,
                'depends': 'infer',
            },
        },
    },
}


def _run_all(session, tasks):
    for name in tasks:
        for tid in tasks[name]:
            execute_by_id(tid, exit=False, session=session)


class TestPipeFlow:
    def test_full_model_lifecycle(self, session):
        tp = TaskProvider(session)
        # 1. train
        _dag, tasks = dag_standard(session, TRAIN_CONFIG)
        _run_all(session, tasks)
        train_tid = tasks['train'][0]
        assert tp.by_id(train_tid).status == int(TaskStatus.Success)

        # 2. register the model from the finished train task
        add_dag = dag_model_add(session, {
            'name': 'prod_model', 'task': train_tid})
        add_tasks = tp.by_dag(add_dag.id)
        for t in add_tasks:
            execute_by_id(t.id, exit=False, session=session)
        model = ModelProvider(session).by_name('prod_model')
        assert model is not None
        assert model.score_local is not None

        # 3. register the pipe
        pipe_dag = dag_pipe(session, PIPE_CONFIG)
        assert pipe_dag.type == int(DagType.Pipe)
        # no tasks created by registration
        assert tp.by_dag(pipe_dag.id) == []

        # 4. start the pipe for the model
        run_dag = dag_model_start(session, {
            'model_id': model.id,
            'dag': pipe_dag.id,
            'pipe': {'name': 'serve_pipe', 'versions': []},
        })
        run_tasks = tp.by_dag(run_dag.id)
        assert len(run_tasks) == 2
        for t in sorted(run_tasks, key=lambda t: t.id):
            execute_by_id(t.id, exit=False, session=session)
        for t in tp.by_dag(run_dag.id):
            assert t.status == int(TaskStatus.Success), t.name
        # the pipe's valid stage scored the model
        model = ModelProvider(session).by_name('prod_model')
        valid_task = [t for t in tp.by_dag(run_dag.id)
                      if t.executor == 'valid'][0]
        assert valid_task.score is not None
        assert valid_task.score > 0.6
        assert model.score_local == pytest.approx(valid_task.score)

    def test_model_add_without_task_creates_row(self, session):
        from mlcomp_tpu.db.providers import ProjectProvider
        p = ProjectProvider(session).add_project('p_pipes_bare')
        result = dag_model_add(session, {
            'name': 'bare_model', 'project': p.id})
        assert result is None
        assert ModelProvider(session).by_name('bare_model') is not None

    def test_pipe_repoints_same_named_models(self, session):
        from mlcomp_tpu.db.models import Model
        from mlcomp_tpu.db.providers import ProjectProvider
        from mlcomp_tpu.utils.misc import now
        p = ProjectProvider(session).add_project('p_pipes_repoint')
        provider = ModelProvider(session)
        config = {
            'info': {'name': 'serve_pipe', 'project': 'p_pipes_repoint'},
            'pipes': {'serve_pipe': {'x': {'type': 'equation'}}},
        }
        first = dag_pipe(session, config)
        provider.add(Model(name='serve_pipe', project=p.id,
                           dag=first.id, created=now()))
        second = dag_pipe(session, config)
        model = provider.by_name('serve_pipe')
        assert model.dag == second.id

    def test_version_overlay_merges_equations(self, session):
        tp = TaskProvider(session)
        _dag, tasks = dag_standard(session, TRAIN_CONFIG)
        _run_all(session, tasks)
        add_dag = dag_model_add(session, {
            'name': 'ver_model', 'task': tasks['train'][0]})
        for t in tp.by_dag(add_dag.id):
            execute_by_id(t.id, exit=False, session=session)
        model = ModelProvider(session).by_name('ver_model')
        pipe_dag = dag_pipe(session, PIPE_CONFIG)
        run_dag = dag_model_start(session, {
            'model_id': model.id,
            'dag': pipe_dag.id,
            'pipe': {
                'name': 'serve_pipe',
                'versions': [{'name': 'v1',
                              'equations': {'infer': {'batch_size': 32}}}],
                'version': {'name': 'v1',
                            'equations': {'infer': {'batch_size': 32}}},
            },
        })
        from mlcomp_tpu.utils.io import yaml_load
        config = yaml_load(DagProvider(session).by_id(run_dag.id).config)
        assert config['executors']['infer']['batch_size'] == 32
        assert config['executors']['infer']['model_name'] == 'ver_model'
        # version usage recorded on the model row
        model = ModelProvider(session).by_name('ver_model')
        eqs = yaml_load(model.equations)
        assert eqs['serve_pipe'][0]['name'] == 'v1'
        assert eqs['serve_pipe'][0].get('used')
