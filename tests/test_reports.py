"""Report builders + plot utils + ReportImg production
(VERDICT round-1 item 5): rows written by builders and executors,
confusion matrix rendered through the API."""

import numpy as np
import pytest

from mlcomp_tpu.db.models import Dag, Task
from mlcomp_tpu.db.providers import (
    ProjectProvider, ReportImgProvider, TaskProvider,
)
from mlcomp_tpu.utils.misc import now
from mlcomp_tpu.utils.plot import (
    bytes_to_img, classification_report_plot, confusion_matrix_plot,
    img_to_bytes, mask_overlay, series_plot,
)


@pytest.fixture()
def task(session):
    p = ProjectProvider(session).add_project('p_reports')
    dag = Dag(name='d', config='', project=p.id, created=now())
    session.add(dag)
    t = Task(name='t', executor='t', dag=dag.id, status=0,
             last_activity=now())
    TaskProvider(session).add(t)
    return t


class TestPlotUtils:
    def test_img_roundtrip(self):
        img = (np.random.rand(16, 16, 3) * 255).astype(np.uint8)
        data = img_to_bytes(img)
        assert data[:2] == b'\xff\xd8'  # jpeg magic
        back = bytes_to_img(data)
        assert back.shape == (16, 16, 3)

    def test_float_image_normalized(self):
        img = np.random.rand(8, 8, 3).astype(np.float32)
        assert img_to_bytes(img)[:2] == b'\xff\xd8'

    def test_confusion_plot(self):
        cm = np.array([[5, 1], [2, 8]])
        data = confusion_matrix_plot(cm, ['cat', 'dog'])
        assert data[:2] == b'\xff\xd8' and len(data) > 1000

    def test_classification_report_plot(self):
        y = np.array([0, 0, 1, 1, 2, 2])
        p = np.array([0, 1, 1, 1, 2, 0])
        assert classification_report_plot(y, p)[:2] == b'\xff\xd8'

    def test_series_plot(self):
        data = series_plot({'loss': [1.0, 0.5, 0.2],
                            'accuracy': [0.3, 0.6, 0.9]}, title='train')
        assert data[:2] == b'\xff\xd8'

    def test_mask_overlay(self):
        img = np.random.rand(8, 8, 3)
        mask = np.zeros((8, 8), np.int64)
        mask[:4] = 1
        out = mask_overlay(img, mask)
        assert out.shape == (8, 8, 3) and out.dtype == np.uint8
        # background rows unchanged beyond scaling, masked rows blended
        assert not np.array_equal(out[:4], out[4:])


class TestBuilders:
    def test_classification_builder_rows(self, session, task):
        from mlcomp_tpu.worker.reports import ClassificationReportBuilder
        n, k = 20, 3
        rng = np.random.RandomState(0)
        imgs = rng.rand(n, 8, 8, 3).astype(np.float32)
        y = rng.randint(0, k, n)
        probs = rng.dirichlet(np.ones(k), n)
        builder = ClassificationReportBuilder(
            session, task, plot_count=5, class_names=['a', 'b', 'c'])
        count = builder.build(imgs, y, probs, epoch=2)
        assert count == 6  # 5 samples + confusion
        provider = ReportImgProvider(session)
        res = provider.get({'task': task.id, 'group': 'img_classify'})
        assert res['total'] == 5
        row = res['data'][0]
        assert row['y'] is not None and row['y_pred'] is not None
        assert row['epoch'] == 2 and row['size'] > 0
        conf = provider.get({'task': task.id,
                             'group': 'img_classify_confusion'})
        assert conf['total'] == 1

    def test_classification_builder_prioritizes_mistakes(self, session,
                                                         task):
        from mlcomp_tpu.worker.reports import ClassificationReportBuilder
        imgs = np.random.rand(10, 4, 4, 3).astype(np.float32)
        y = np.zeros(10, np.int64)
        probs = np.zeros((10, 2))
        probs[:8, 0] = 1.0          # 8 confident corrects
        probs[8:, 1] = 1.0          # 2 confident mistakes
        builder = ClassificationReportBuilder(session, task, plot_count=2)
        builder.build(imgs, y, probs)
        rows = ReportImgProvider(session).get(
            {'task': task.id, 'group': 'img_classify'})['data']
        assert all(r['y'] != r['y_pred'] for r in rows)

    def test_segmentation_builder_rows(self, session, task):
        from mlcomp_tpu.worker.reports import SegmentationReportBuilder
        n = 6
        imgs = np.random.rand(n, 16, 16, 3).astype(np.float32)
        masks = np.zeros((n, 16, 16), np.int32)
        masks[:, :8] = 1
        preds = np.array(masks)
        preds[0] = 0  # one total miss
        builder = SegmentationReportBuilder(session, task, plot_count=3)
        count = builder.build(imgs, masks, preds)
        assert count == 3
        rows = ReportImgProvider(session).get(
            {'task': task.id, 'group': 'img_segment'})['data']
        assert rows[0]['score'] is not None
        scores = sorted(r['score'] for r in rows)
        assert scores[0] == 0.0  # the total miss is included (worst-first)

    def test_confusion_matrix_via_provider(self, session, task):
        from mlcomp_tpu.worker.reports import ClassificationReportBuilder
        imgs = np.random.rand(12, 4, 4, 3).astype(np.float32)
        y = np.array([0, 1] * 6)
        probs = np.eye(2)[(y + np.arange(12) % 2) % 2]
        ClassificationReportBuilder(session, task, plot_count=12).build(
            imgs, y, probs)
        cm = ReportImgProvider(session).confusion_matrix({'task': task.id})
        assert cm['n'] == 2
        assert sum(sum(r) for r in cm['matrix']) == 12


class TestApiRender:
    def test_img_classify_endpoint_renders(self, session, task):
        """The api_img_classify handler returns base64 imgs + confusion
        (VERDICT 'done' criterion for item 5)."""
        import base64
        from mlcomp_tpu.server.api import api_img_classify
        from mlcomp_tpu.worker.reports import ClassificationReportBuilder
        imgs = np.random.rand(8, 8, 8, 3).astype(np.float32)
        y = np.arange(8) % 2
        probs = np.eye(2)[y]
        ClassificationReportBuilder(session, task, plot_count=4).build(
            imgs, y, probs)
        res = api_img_classify({'task': task.id, 'group': 'img_classify'},
                               session)
        assert res['total'] == 4
        raw = base64.b64decode(res['data'][0]['img'])
        assert raw[:2] == b'\xff\xd8'
        assert res['confusion']['n'] == 2


class TestExecutorWiring:
    def test_valid_classify_plot_hooks(self, session, task, tmp_path,
                                       monkeypatch):
        monkeypatch.chdir(tmp_path)
        from mlcomp_tpu.worker.executors import Executor
        rng = np.random.RandomState(0)
        x = rng.rand(16, 8, 8, 3).astype(np.float32)
        y = (np.arange(16) % 3).astype(np.int32)
        np.savez('d.npz', x=x, y=y)
        import os
        os.makedirs('data/pred')
        np.save('data/pred/mm.npy', np.eye(3)[y])
        ex = Executor.get('valid_classify')(
            name='mm', dataset={'path': 'd.npz'}, layout='base',
            plot_count=4)
        ex.task = task
        ex.session = session
        result = ex.work()
        assert result['score'] == 1.0
        provider = ReportImgProvider(session)
        assert provider.get({'task': task.id,
                             'group': 'img_classify'})['total'] == 4
        assert provider.get({'task': task.id,
                             'group': 'classification_report'})['total'] == 1
        assert provider.get(
            {'task': task.id, 'group': 'img_classify_confusion'}
        )['total'] == 1

    def test_jax_train_report_imgs(self, session, task, tmp_path):
        from mlcomp_tpu.train import JaxTrain
        ex = JaxTrain(
            model={'name': 'mlp', 'num_classes': 4, 'hidden': [16],
                   'dtype': 'float32'},
            dataset={'name': 'synthetic_images', 'n_train': 128,
                     'n_valid': 32, 'image_size': 8, 'channels': 1,
                     'num_classes': 4},
            batch_size=32, epochs=1,
            checkpoint_dir=str(tmp_path / 'ck'),
            report_imgs={'type': 'classification', 'plot_count': 6})
        from test_train import DummyStep
        ex.step = DummyStep()
        ex.task = task
        ex.session = session
        ex.additional_info = {}
        ex.dag = None
        ex.work()
        provider = ReportImgProvider(session)
        assert provider.get({'task': task.id,
                             'group': 'img_classify'})['total'] == 6
        assert provider.get(
            {'task': task.id, 'group': 'img_classify_confusion'}
        )['total'] == 1


class TestDescribe:
    def test_dag_summary_and_render(self, session):
        """describe-style dashboard (reference utils/describe.py):
        summary assembly + a rendered figure for a real executed DAG."""
        from mlcomp_tpu.server.create_dags import dag_standard
        from mlcomp_tpu.utils.describe import dag_summary, describe
        from mlcomp_tpu.worker.tasks import execute_by_id

        config = {
            'info': {'name': 'desc_dag', 'project': 'p_describe'},
            'executors': {
                'train': {
                    'type': 'jax_train',
                    'model': {'name': 'mlp', 'num_classes': 4,
                              'hidden': [16], 'dtype': 'float32'},
                    'dataset': {'name': 'synthetic_images',
                                'n_train': 128, 'n_valid': 32,
                                'image_size': 8, 'channels': 1,
                                'num_classes': 4},
                    'batch_size': 32, 'epochs': 2,
                },
                'probe': {'type': 'split', 'variant': 'count',
                          'count': 10, 'depends': 'train'},
            },
        }
        dag, tasks = dag_standard(session, config)
        for name in ('train', 'probe'):
            for tid in tasks[name]:
                execute_by_id(tid, exit=False, session=session)

        summary = dag_summary(dag.id, session)
        assert len(summary['tasks']) == 2
        assert all(r['status'] == 'Success' for r in summary['tasks'])
        assert len(summary['graph']['nodes']) == 2
        assert len(summary['graph']['edges']) == 1
        # per-epoch training series present
        assert any('accuracy' in k for k in summary['series'])
        assert summary['logs']

        fig = describe(dag.id, session)
        assert fig is not None
        import matplotlib.pyplot as plt
        plt.close(fig)
