"""User code for the real-image segmentation-ensemble DAG: one tiny
executor that materializes the label frame the framework Split executor
stratifies.

Everything else in the DAG is framework machinery (prepare → split →
two unet ``jax_train`` tasks with ``infer_valid`` prediction dumps →
``valid_segment`` on member A and on the ensemble average); parity
target is the reference's Severstal segmentation ensemble (BASELINE
config #5: split → train unets → infer → ensemble), with sklearn's real
handwritten-digit scans — masks derived by foreground thresholding —
standing in for the Kaggle download in a zero-egress environment.
"""

import os

from mlcomp_tpu.worker.executors import Executor


@Executor.register
class PrepareDigitsLabels(Executor):
    """Write data/labels.csv (one row per load_digits sample, in order)
    for the stratified Split executor."""

    def work(self):
        import pandas as pd
        from sklearn.datasets import load_digits

        os.makedirs('data', exist_ok=True)
        y = load_digits().target
        out = os.path.join('data', 'labels.csv')
        pd.DataFrame({'sample': range(len(y)), 'label': y}).to_csv(
            out, index=False)
        self.info(f'wrote {len(y)} real digit labels -> {out}')
        return {'count': int(len(y))}
