"""User code for the real-data digits DAG: one tiny executor that
materializes the label frame the framework Split executor stratifies.

Everything else in the DAG is framework machinery (split → jax_train →
infer_classify → valid_classify); parity target is the reference's
digit-recognizer example (reference examples/digit-recognizer/Readme.md)
with sklearn's real handwritten-digit scans standing in for the Kaggle
download in a zero-egress environment.
"""

import os

from mlcomp_tpu.worker.executors import Executor


@Executor.register
class PrepareDigitsLabels(Executor):
    """Write data/labels.csv (one row per load_digits sample, in order)
    for the stratified Split executor."""

    def work(self):
        import pandas as pd
        from sklearn.datasets import load_digits

        os.makedirs('data', exist_ok=True)
        y = load_digits().target
        out = os.path.join('data', 'labels.csv')
        pd.DataFrame({'sample': range(len(y)), 'label': y}).to_csv(
            out, index=False)
        self.info(f'wrote {len(y)} real digit labels -> {out}')
        return {'count': int(len(y))}
