"""User-code executors for the digit-recognizer example.

Demonstrates the minimum end-to-end slice (SURVEY.md §7 step 2): a
download→split→train→infer DAG where training is a jit'd JAX MLP step.
Data is synthetic (class-conditional patterns) because the build
environment has no network egress; the learning task is real.
"""

import functools
import os

import numpy as np

from mlcomp_tpu.worker.executors import Executor


def data_dir(config):
    folder = os.path.join('data', 'digits')
    os.makedirs(folder, exist_ok=True)
    return folder


def synth_digits(n, seed=0):
    """Synthetic 28x28 'digit' images: each class is a fixed random
    prototype + noise. Linearly separable-ish, learnable to ~99%."""
    rng = np.random.RandomState(seed)
    prototypes = rng.rand(10, 28 * 28).astype(np.float32)
    y = rng.randint(0, 10, size=n)
    x = prototypes[y] + 0.35 * rng.randn(n, 28 * 28).astype(np.float32)
    return x.astype(np.float32), y.astype(np.int32)


@Executor.register
class PrepareDigits(Executor):
    def __init__(self, n_samples: int = 4096, **kwargs):
        self.n_samples = n_samples

    def work(self):
        folder = data_dir(self.config)
        x, y = synth_digits(self.n_samples)
        np.savez(os.path.join(folder, 'digits.npz'), x=x, y=y)
        self.info(f'prepared {self.n_samples} samples -> {folder}')


@Executor.register
class SplitDigits(Executor):
    def __init__(self, n_folds: int = 5, **kwargs):
        self.n_folds = n_folds

    def work(self):
        folder = data_dir(self.config)
        data = np.load(os.path.join(folder, 'digits.npz'))
        n = len(data['y'])
        folds = np.arange(n) % self.n_folds
        np.random.RandomState(0).shuffle(folds)
        np.save(os.path.join(folder, 'fold.npy'), folds)
        self.info(f'split {n} samples into {self.n_folds} folds')


@Executor.register
class TrainDigits(Executor):
    def __init__(self, epochs: int = 3, batch_size: int = 256,
                 lr: float = 1e-3, hidden: int = 256, **kwargs):
        self.epochs = epochs
        self.batch_size = batch_size
        self.lr = lr
        self.hidden = hidden

    def work(self):
        import jax
        import jax.numpy as jnp
        import optax

        folder = data_dir(self.config)
        data = np.load(os.path.join(folder, 'digits.npz'))
        folds = np.load(os.path.join(folder, 'fold.npy'))
        x, y = data['x'], data['y']
        train_mask = folds != 0
        xt, yt = x[train_mask], y[train_mask]
        xv, yv = x[~train_mask], y[~train_mask]

        key = jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(key)
        params = {
            'w1': jax.random.normal(k1, (784, self.hidden)) * 0.05,
            'b1': jnp.zeros(self.hidden),
            'w2': jax.random.normal(k2, (self.hidden, 10)) * 0.05,
            'b2': jnp.zeros(10),
        }
        tx = optax.adam(self.lr)
        opt_state = tx.init(params)

        def forward(params, xb):
            h = jax.nn.relu(xb @ params['w1'] + params['b1'])
            return h @ params['w2'] + params['b2']

        def loss_fn(params, xb, yb):
            logits = forward(params, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()

        # donate the carried params/opt_state so XLA reuses their
        # buffers instead of holding two copies live per step
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, xb, yb):
            loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb)
            updates, opt_state = tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        @jax.jit
        def accuracy(params, xb, yb):
            return (forward(params, xb).argmax(-1) == yb).mean()

        n = len(xt)
        steps = max(1, n // self.batch_size)
        for epoch in range(self.epochs):
            self.step.start(2, f'epoch_{epoch}')
            perm = np.random.RandomState(epoch).permutation(n)
            losses = []
            for s in range(steps):
                idx = perm[s * self.batch_size:(s + 1) * self.batch_size]
                params, opt_state, loss = train_step(
                    params, opt_state, jnp.asarray(xt[idx]),
                    jnp.asarray(yt[idx]))
                losses.append(float(loss))
            acc = float(accuracy(params, jnp.asarray(xv), jnp.asarray(yv)))
            self.info(
                f'epoch {epoch}: loss={np.mean(losses):.4f} acc={acc:.4f}')

        os.makedirs('models', exist_ok=True)
        np.savez(os.path.join('models', 'digits_mlp.npz'),
                 **{k: np.asarray(v) for k, v in params.items()})
        self.task.score = acc
        from mlcomp_tpu.db.providers import TaskProvider
        TaskProvider(self.session).update(self.task, ['score'])
        return {'accuracy': acc}


@Executor.register
class InferDigits(Executor):
    def __init__(self, **kwargs):
        pass

    def work(self):
        import jax
        import jax.numpy as jnp

        folder = data_dir(self.config)
        data = np.load(os.path.join(folder, 'digits.npz'))
        weights = np.load(os.path.join('models', 'digits_mlp.npz'))

        def forward(xb):
            h = jax.nn.relu(xb @ weights['w1'] + weights['b1'])
            return h @ weights['w2'] + weights['b2']

        preds = np.asarray(
            jax.jit(forward)(jnp.asarray(data['x'][:512])).argmax(-1))
        out = os.path.join(folder, 'predictions.npy')
        np.save(out, preds)
        acc = float((preds == data['y'][:512]).mean())
        self.info(f'inferred 512 samples, acc={acc:.4f} -> {out}')
        return {'n': len(preds), 'accuracy': acc}
