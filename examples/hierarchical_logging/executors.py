"""Hierarchical step-tree demo (parity: reference
examples/hierarchical_logging/executors.py:4-20).

Each ``self.step.start(level, name)`` opens a step at that depth;
opening a step at level N auto-closes anything at level >= N, and every
log line attaches to the innermost open step. The UI's task detail and
``python -m mlcomp_tpu`` describe render the resulting tree with
per-step durations and log counts.
"""

import time

from mlcomp_tpu.worker.executors import Executor


@Executor.register
class StepTreeDemo(Executor):
    def __init__(self, stages: int = 2, substeps: int = 3, **kwargs):
        super().__init__(**kwargs)
        self.stages = int(stages)
        self.substeps = int(substeps)

    def work(self):
        for s in range(self.stages):
            self.step.start(1, f'stage {s}', s)
            self.info(f'stage {s} begins')
            for i in range(self.substeps):
                self.step.start(2, f'substep {i}', i)
                time.sleep(0.01)
                self.info(f'work item {i} done')
        return {'stages': self.stages, 'substeps': self.substeps}
